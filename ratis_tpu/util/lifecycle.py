"""Component lifecycle state machine.

Capability parity with the reference's LifeCycle
(ratis-common/src/main/java/org/apache/ratis/util/LifeCycle.java): a named
state machine with a fixed legal-transition graph, used by servers, logs and
transports to guard start/close ordering.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Iterable


class LifeCycleState(enum.Enum):
    NEW = "NEW"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    PAUSING = "PAUSING"
    PAUSED = "PAUSED"
    EXCEPTION = "EXCEPTION"
    CLOSING = "CLOSING"
    CLOSED = "CLOSED"

    def is_closing_or_closed(self) -> bool:
        return self in (LifeCycleState.CLOSING, LifeCycleState.CLOSED)

    def is_running(self) -> bool:
        return self is LifeCycleState.RUNNING

    def is_paused(self) -> bool:
        return self in (LifeCycleState.PAUSING, LifeCycleState.PAUSED)


S = LifeCycleState

# Legal predecessor sets (mirrors the reference's transition graph,
# LifeCycle.java "State.isValid").
_PREDECESSORS: dict[LifeCycleState, frozenset[LifeCycleState]] = {
    S.NEW: frozenset({S.STARTING}),
    S.STARTING: frozenset({S.NEW, S.PAUSED}),
    S.RUNNING: frozenset({S.STARTING}),
    S.PAUSING: frozenset({S.RUNNING}),
    S.PAUSED: frozenset({S.PAUSING}),
    S.EXCEPTION: frozenset({S.STARTING, S.PAUSING, S.RUNNING}),
    S.CLOSING: frozenset({S.STARTING, S.RUNNING, S.PAUSING, S.PAUSED, S.EXCEPTION}),
    S.CLOSED: frozenset({S.NEW, S.CLOSING}),
}


class LifeCycle:
    def __init__(self, name: str):
        self._name = name
        self._state = S.NEW
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def get_current_state(self) -> LifeCycleState:
        return self._state

    def transition(self, to: LifeCycleState) -> None:
        with self._lock:
            if self._state not in _PREDECESSORS[to]:
                raise IllegalLifeCycleTransition(
                    f"{self._name}: illegal transition {self._state.value} -> {to.value}"
                )
            self._state = to

    def transition_if_not_equal(self, to: LifeCycleState) -> bool:
        with self._lock:
            if self._state is to:
                return False
            if self._state not in _PREDECESSORS[to]:
                raise IllegalLifeCycleTransition(
                    f"{self._name}: illegal transition {self._state.value} -> {to.value}"
                )
            self._state = to
            return True

    def compare_and_transition(self, expected: LifeCycleState, to: LifeCycleState) -> bool:
        with self._lock:
            if self._state is not expected:
                return False
            self._state = to
            return True

    def assert_current_state(self, expected: Iterable[LifeCycleState] | LifeCycleState) -> None:
        states = (expected,) if isinstance(expected, LifeCycleState) else tuple(expected)
        if self._state not in states:
            raise IllegalLifeCycleTransition(
                f"{self._name}: state is {self._state.value}, expected one of "
                f"{[s.value for s in states]}"
            )

    def start_and_transition(self, start: Callable[[], None]) -> None:
        """Run ``start`` bracketed by STARTING -> RUNNING, EXCEPTION on error."""
        self.transition(S.STARTING)
        try:
            start()
            self.transition(S.RUNNING)
        except Exception:
            self.transition(S.EXCEPTION)
            raise

    def check_state_and_close(self, close: Callable[[], None]) -> bool:
        with self._lock:
            if self._state.is_closing_or_closed():
                return False
            # NEW -> CLOSED directly (nothing started); otherwise via CLOSING,
            # matching the reference graph (LifeCycle.java:97-104).
            self._state = S.CLOSED if self._state is S.NEW else S.CLOSING
            if self._state is S.CLOSED:
                return True
        try:
            close()
        finally:
            with self._lock:
                self._state = S.CLOSED
        return True

    def __str__(self) -> str:
        return f"{self._name}:{self._state.value}"


class IllegalLifeCycleTransition(RuntimeError):
    pass
