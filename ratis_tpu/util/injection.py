"""Named fault-injection points compiled into production code paths.

Capability parity with the reference's CodeInjectionForTesting
(ratis-common/src/main/java/org/apache/ratis/util/CodeInjectionForTesting.java:29-60):
production code calls ``execute(point, local_id, *args)`` at named points;
tests register sync or async callbacks to block/delay/fail those points.
No-op (one dict lookup) when nothing is registered.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Callable, Optional

# Well-known injection point names (mirroring the reference's usage sites).
APPEND_TRANSACTION = "append_transaction"       # RaftServerImpl.java:822
LOG_SYNC = "log_sync"                           # RaftServerImpl.java:1620
RUN_LOG_WORKER = "run_log_worker"               # SegmentedRaftLogWorker.java:70
REQUEST_VOTE = "request_vote"
APPEND_ENTRIES = "append_entries"
INSTALL_SNAPSHOT = "install_snapshot"

_injections: dict[str, Callable[..., Any]] = {}


def put(point: str, code: Callable[..., Any]) -> None:
    _injections[point] = code


def remove(point: str) -> None:
    _injections.pop(point, None)


def clear() -> None:
    _injections.clear()


def is_registered(point: str) -> bool:
    return point in _injections


async def execute(point: str, local_id: Any = None, remote_id: Any = None,
                  *args: Any) -> bool:
    """Run the injected code if any; returns True iff an injection ran.
    Sync and async callbacks are both supported."""
    code = _injections.get(point)
    if code is None:
        return False
    result = code(local_id, remote_id, *args)
    if inspect.isawaitable(result):
        await result
    return True


def execute_sync(point: str, local_id: Any = None, remote_id: Any = None,
                 *args: Any) -> bool:
    code = _injections.get(point)
    if code is None:
        return False
    code(local_id, remote_id, *args)
    return True
