"""Sliding windows for ordered async streaming.

Capability parity with the reference's SlidingWindow
(ratis-common/src/main/java/org/apache/ratis/util/SlidingWindow.java:39):

- ``SlidingWindowClient``: assigns consecutive seqNums to submitted requests,
  tracks replies, supports first-request flagging after leader failover and
  bulk retry from a given seqNum (SlidingWindow.java:277,349,325).
- ``SlidingWindowServer``: delays out-of-order requests until all lower
  seqNums have been processed, so the server applies an ordered stream even
  over an unordered transport.

asyncio-native: no locks; all methods must be called from the event loop.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Generic, Optional, TypeVar

REQ = TypeVar("REQ")
REP = TypeVar("REP")


class SlidingWindowClient(Generic[REQ]):
    def __init__(self, name: str = ""):
        self._name = name
        self._next_seq = 0
        self._first_seq = -1  # seqNum of the current "first" (post-failover) request
        self._requests: dict[int, REQ] = {}

    def next_seq_num(self) -> int:
        seq = self._next_seq
        self._next_seq += 1
        return seq

    def submit_new_request(self, make_request: Callable[[int], REQ]) -> REQ:
        seq = self.next_seq_num()
        request = make_request(seq)
        self._requests[seq] = request
        if self._first_seq < 0:
            self._first_seq = seq
        return request

    def is_first(self, seq: int) -> bool:
        return seq == self._first_seq

    def receive_reply(self, seq: int) -> None:
        self._requests.pop(seq, None)
        if seq == self._first_seq:
            self._first_seq = min(self._requests) if self._requests else -1

    def pending_requests(self) -> list[REQ]:
        return [self._requests[k] for k in sorted(self._requests)]

    def reset_first_seq(self) -> None:
        """After failover, the lowest outstanding request becomes 'first' again
        so the new server resets its processing window."""
        self._first_seq = min(self._requests) if self._requests else -1

    def size(self) -> int:
        return len(self._requests)


class SlidingWindowServer(Generic[REQ]):
    """Processes requests strictly in seqNum order.

    ``receive(seq, is_first, request)`` either dispatches immediately (when
    seq == nextToProcess) plus any queued successors, or parks the request.
    """

    def __init__(self, process: Callable[[REQ], Awaitable[None]], name: str = "",
                 on_drop: Optional[Callable[[REQ], None]] = None):
        self._process = process
        self._name = name
        self._on_drop = on_drop  # parked item discarded by a first-rebase
        self._next_to_process: Optional[int] = None
        self._pending: dict[int, REQ] = {}
        self._drain_lock = asyncio.Lock()

    async def receive(self, seq: int, is_first: bool, request: REQ) -> bool:
        """Returns False for a duplicate of an already-processed seq — the
        caller must answer it out-of-band (retry cache), since no process()
        call will ever see it."""
        if self._next_to_process is not None and seq < self._next_to_process:
            # Duplicate of an already-released request — even when flagged
            # first: a late dup of a first request must NOT rewind the
            # window (already-processed successors would never re-arrive,
            # stalling everything behind a permanent gap).
            return False
        if is_first:
            self._next_to_process = seq
            # A post-failover "first" request resets the window; anything
            # parked below it can never be processed — hand it back so the
            # caller resolves its reply future instead of leaking it.
            for stale in [s for s in self._pending if s < seq]:
                item = self._pending.pop(stale)
                if self._on_drop is not None:
                    self._on_drop(item)
        elif self._next_to_process is None:
            # Window not yet based: park until the first-flagged request
            # arrives (it reorders ahead of this one in flight).  If it was
            # lost, the client's retry re-flags the lowest outstanding seq
            # as first and rebases us (SlidingWindow.java:277).
            self._park(seq, request)
            return True
        self._park(seq, request)
        # Serialize processing: without the lock, a receive() arriving while a
        # predecessor's process() is awaited would dispatch out of order.
        async with self._drain_lock:
            while self._next_to_process in self._pending:
                req = self._pending.pop(self._next_to_process)
                # Increment before the await so a duplicate arriving while
                # process() runs fails the `seq < next` check and is dropped;
                # ordering is still guaranteed by the lock held across the await.
                self._next_to_process += 1
                await self._process(req)
        return True

    def _park(self, seq: int, request: REQ) -> None:
        """Park a request; a retry displacing an already-parked copy of the
        same seq hands the old item to on_drop so its reply future resolves
        instead of leaking (the retry's future is the live one)."""
        old = self._pending.get(seq)
        self._pending[seq] = request
        if old is not None and self._on_drop is not None:
            self._on_drop(old)

    def pending_count(self) -> int:
        return len(self._pending)

    def drain_parked(self) -> list[REQ]:
        """Remove and return every parked request (step-down/close: the
        gaps they wait on will never be filled here)."""
        parked = [self._pending[s] for s in sorted(self._pending)]
        self._pending.clear()
        return parked
