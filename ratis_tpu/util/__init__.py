from ratis_tpu.util.timeduration import TimeDuration
from ratis_tpu.util.lifecycle import LifeCycle, LifeCycleState
