"""Host-path tracing: request->commit spans over the five-layer request path.

No reference analog — the reference leans on JVM profilers; here the host
runtime is a single asyncio loop and the question every perf round asks is
"which host-side stage eats the commit's wall-clock?" (VERDICT r5: the
1025 commits/s headline had no artifact decomposing msgpack / socket /
division-append / engine-dispatch cost).  This module answers it with
always-available, low-overhead structured spans:

- A :class:`TraceContext` is just an integer trace id minted at the client
  (``Tracer.begin_trace``), carried on :class:`RaftClientRequest` (wire
  field ``tr``) through the transport codec, server routing, the division
  write path, and apply — every stage the request crosses records a span
  against the same id.
- Span records are written to fixed-size per-stage ring buffers
  (:class:`SpanRing`): a pre-allocated int64 array, one row assignment per
  record — no allocation on the hot path, bounded memory, and a high-rate
  stage (codec) can never evict a low-rate one (client spans).
- Sampling (``raft.tpu.trace.sample-every``) bounds the recording rate;
  with tracing disabled (the default) every instrumentation site is a
  single attribute check.

Aggregation/export (Chrome trace-event JSON for Perfetto, and the
per-stage percentile decomposition table) lives in
:mod:`ratis_tpu.trace.export`.

The runtime is single-event-loop end to end, so one process-wide tracer
(``TRACER`` / :func:`get_tracer`) serves every co-hosted server and the
in-process clients; cross-process propagation rides the wire field.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time

import numpy as np

# Transport ingress timestamp for the in-flight request: the transport sets
# it just before handing off to the server handler, and the handler's route
# span starts there — so the task-scheduling hop between ingress and the
# handler's first instruction is ATTRIBUTED (it is real latency), not lost
# to the coverage residual.  A ContextVar propagates into the handler task
# (task creation copies the caller's context); single-use — the reader
# clears it.
INGRESS_NS: contextvars.ContextVar[int] = contextvars.ContextVar(
    "ratis_trace_ingress_ns", default=0)

# Stage ids.  The SERVER-side stages route/txn_start/append/replicate/apply
# TILE the request's server wall-clock (each starts where the previous
# ends), so their per-trace sum is directly comparable to the client span.
# CLIENT / WIRE / ENGINE overlap other stages (marked in export).
STAGE_CLIENT = 0      # client.send — full client-observed request wall
STAGE_ENCODE = 1      # codec.encode — msgpack encode (request or server rpc)
STAGE_DECODE = 2      # codec.decode — msgpack decode
STAGE_WIRE = 3        # wire.rtt — transport send + reply (overlaps server)
STAGE_ROUTE = 4       # server.route — handler entry -> division submit
STAGE_TXN = 5         # server.txn_start — SM start/pre-append hooks
STAGE_APPEND = 6      # server.append — leader log append (in-memory)
STAGE_REPLICATE = 7   # server.replicate — append done -> apply starts
                      # (quorum wait + apply-queue wait)
STAGE_APPLY = 8       # server.apply — state-machine apply
STAGE_REPLY = 9       # server.reply — apply done -> write handler resumes
                      # (reply-future resolution + event-loop scheduling)
STAGE_RESPOND = 10    # server.respond — server handler done -> reply handed
                      # back to the transport / written to the socket
STAGE_ENGINE = 11     # engine.dispatch — one quorum-engine tick dispatch
STAGE_FANOUT = 12     # server.fanout — one waterline reply fan-out pass
                      # (batch of committed requests resolved in one unit;
                      # tag = batch size; process-level like engine.dispatch)
NUM_STAGES = 13

STAGE_NAMES = (
    "client.send", "codec.encode", "codec.decode", "wire.rtt",
    "server.route", "server.txn_start", "server.append",
    "server.replicate", "server.apply", "server.reply", "server.respond",
    "engine.dispatch", "server.fanout",
)

# Stages whose durations tile the per-request path (no mutual overlap):
# these are the ones the decomposition's coverage fraction sums.
TILING_STAGES = (STAGE_ENCODE, STAGE_DECODE, STAGE_ROUTE, STAGE_TXN,
                 STAGE_APPEND, STAGE_REPLICATE, STAGE_APPLY, STAGE_REPLY,
                 STAGE_RESPOND)


class SpanRing:
    """Fixed-size span ring for ONE stage.

    Records are rows of a pre-allocated ``[capacity, 5]`` int64 array
    (trace_id, t0_ns, dur_ns, tag, origin_thread) — recording is one row
    assignment, no allocation, and wraparound overwrites the oldest record.
    With loop sharding (raft.tpu.server.loop-shards) stages record from
    several event-loop threads into the same ring, so the row slot is
    claimed under a lock and each span carries its origin thread id (the
    Chrome export maps it to a per-shard track)."""

    COLS = 5

    __slots__ = ("capacity", "_buf", "_n", "_lock")

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._buf = np.zeros((self.capacity, self.COLS), np.int64)
        self._n = 0
        self._lock = threading.Lock()

    def record(self, trace_id: int, t0_ns: int, t1_ns: int,
               tag: int = 0, origin: int = 0) -> None:
        with self._lock:
            row = self._buf[self._n % self.capacity]
            self._n += 1
        row[0] = trace_id
        row[1] = t0_ns
        row[2] = t1_ns - t0_ns
        row[3] = tag
        row[4] = origin

    @property
    def count(self) -> int:
        """Records currently held (<= capacity)."""
        return min(self._n, self.capacity)

    @property
    def recorded(self) -> int:
        """Records ever written (wraparound keeps only the last capacity)."""
        return self._n

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def rows(self) -> np.ndarray:
        """Held records, oldest first, as an [n, 4] array copy."""
        if self._n <= self.capacity:
            return self._buf[:self._n].copy()
        i = self._n % self.capacity
        return np.concatenate([self._buf[i:], self._buf[:i]])

    def clear(self) -> None:
        self._n = 0


class Tracer:
    """Process-wide span recorder.  Disabled (the default) it costs one
    attribute check per instrumentation site; enabled, each Nth request
    (``sample_every``) gets a trace id and its stages record spans."""

    DEFAULT_RING_SIZE = 4096

    def __init__(self):
        self.enabled = False
        self.sample_every = 1
        self.ring_size = self.DEFAULT_RING_SIZE
        self._rings: list[SpanRing] = [SpanRing(1) for _ in range(NUM_STAGES)]
        self._ids = itertools.count(1)
        self._req_tick = 0
        self._proc_tick = 0
        # trace_id -> server-handler-done ns (mark_egress/pop_egress): lets
        # the TRANSPORT close the respond span across the task boundary the
        # handler's return crosses (a ContextVar cannot flow back out of
        # the handler task — task creation copies the context one way).
        self._egress: dict[int, int] = {}

    # -- configuration -------------------------------------------------------

    def configure(self, enabled: bool = True, sample_every: int = 1,
                  ring_size: int = DEFAULT_RING_SIZE) -> None:
        """(Re)configure; allocates fresh rings (existing records drop)."""
        self.sample_every = max(1, int(sample_every))
        self.ring_size = max(1, int(ring_size))
        self._rings = [SpanRing(self.ring_size) for _ in range(NUM_STAGES)]
        self._req_tick = 0
        self._proc_tick = 0
        self._egress = {}
        self.enabled = bool(enabled)

    def reset(self) -> None:
        """Drop recorded spans; keep configuration."""
        for ring in self._rings:
            ring.clear()
        self._egress.clear()

    # -- hot path ------------------------------------------------------------

    @staticmethod
    def now() -> int:
        return time.monotonic_ns()

    def begin_trace(self) -> int:
        """Mint a trace id for a new client request, or 0 when this request
        is not sampled (callers skip every record for id 0)."""
        if not self.enabled:
            return 0
        self._req_tick += 1
        if self._req_tick % self.sample_every:
            return 0
        return next(self._ids)

    def sample(self) -> bool:
        """Sampling decision for PROCESS-level stages (codec on server
        RPCs, engine dispatch) that have no request trace id."""
        if not self.enabled:
            return False
        self._proc_tick += 1
        return self._proc_tick % self.sample_every == 0

    def record(self, trace_id: int, stage: int, t0_ns: int, t1_ns: int,
               tag: int = 0) -> None:
        if not self.enabled:
            return
        self._rings[stage].record(trace_id, t0_ns, t1_ns, tag,
                                  origin=threading.get_ident())

    def mark_egress(self, trace_id: int) -> None:
        """Server handler is done with this request NOW; the transport pops
        the mark to record the respond span (serialize + hand-back/socket
        write).  Bounded: a transport path that never pops (e.g. a direct
        division submit) must not leak entries forever."""
        if not self.enabled or not trace_id:
            return
        if len(self._egress) > 8192:
            self._egress.clear()
        self._egress[trace_id] = time.monotonic_ns()

    def pop_egress(self, trace_id: int) -> int:
        if not self._egress:
            return 0
        return self._egress.pop(trace_id, 0)

    # -- aggregation ---------------------------------------------------------

    def snapshot(self) -> list[tuple[int, int, int, int, int, int]]:
        """Every held record as
        (trace_id, stage, t0_ns, dur_ns, tag, origin_thread)."""
        out: list[tuple[int, int, int, int, int, int]] = []
        for stage, ring in enumerate(self._rings):
            for tid, t0, dur, tag, origin in ring.rows().tolist():
                out.append((tid, stage, t0, dur, tag, origin))
        return out

    def stage_dropped(self) -> dict[str, int]:
        return {STAGE_NAMES[i]: r.dropped
                for i, r in enumerate(self._rings) if r.dropped}


TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER


def configure_from_properties(p) -> None:
    """Enable the process tracer when ``raft.tpu.trace.enabled`` is set.
    Never disables: co-hosted servers share ONE tracer, and a second
    server built without the key must not silence the first's tracing."""
    if p is None:
        return
    from ratis_tpu.conf.keys import RaftServerConfigKeys
    K = RaftServerConfigKeys.Trace
    if K.enabled(p) and not TRACER.enabled:
        TRACER.configure(enabled=True, sample_every=K.sample_every(p),
                         ring_size=K.ring_size(p))
