"""Aggregation and export for host-path traces.

Two consumers of :meth:`Tracer.snapshot`:

- :func:`host_path_decomposition` — the compact per-stage percentile table
  the bench embeds (``host_path_decomposition`` block): where each commit's
  wall-clock goes, stage by stage, with a coverage fraction proving the
  stages account for the measured latency instead of hand-waving at "the
  host runtime".
- :func:`to_chrome_trace` / :func:`write_chrome_trace` — Chrome
  trace-event JSON (the ``traceEvents`` array format), loadable in
  Perfetto (ui.perfetto.dev) or chrome://tracing: one complete-event
  ("ph": "X") per span, one track per trace id.
"""

from __future__ import annotations

import json

import numpy as np

from ratis_tpu.trace.tracer import (NUM_STAGES, STAGE_CLIENT, STAGE_NAMES,
                                    TILING_STAGES)

# Stages whose spans OVERLAP others (client total, transport rtt, engine
# dispatch): reported in the table, excluded from the coverage sum.
_TILING = set(TILING_STAGES)


def _percentile(sorted_ns: list[int], q: float) -> float:
    n = len(sorted_ns)
    return sorted_ns[min(n - 1, int(n * q))] / 1e3  # -> microseconds


def host_path_decomposition(records) -> dict:
    """Per-stage decomposition of the traced request path.

    ``records`` is a ``Tracer.snapshot()`` list of
    (trace_id, stage, t0_ns, dur_ns, tag).

    Coverage is computed per-trace: for every trace id that has a
    ``client.send`` span (the wall-clock denominator), sum the durations of
    its TILING stages (encode/decode/route/txn_start/append/replicate/
    apply — non-overlapping by construction) and divide by the client
    wall.  A coverage near 1.0 means the table explains where the latency
    goes; the residual is event-loop scheduling plus (over real sockets)
    wire time."""
    by_stage: dict[int, list[int]] = {s: [] for s in range(NUM_STAGES)}
    client_wall: dict[int, int] = {}
    covered: dict[int, int] = {}
    for rec in records:
        tid, stage, _t0, dur = rec[0], rec[1], rec[2], rec[3]
        by_stage[stage].append(dur)
        if stage == STAGE_CLIENT and tid:
            client_wall[tid] = client_wall.get(tid, 0) + dur
        elif stage in _TILING and tid:
            covered[tid] = covered.get(tid, 0) + dur

    stages = {}
    for stage in range(NUM_STAGES):
        durs = by_stage[stage]
        if not durs:
            continue
        durs.sort()
        stages[STAGE_NAMES[stage]] = {
            "count": len(durs),
            "p50_us": round(_percentile(durs, 0.50), 1),
            "p90_us": round(_percentile(durs, 0.90), 1),
            "p99_us": round(_percentile(durs, 0.99), 1),
            "mean_us": round(sum(durs) / len(durs) / 1e3, 1),
            "total_ms": round(sum(durs) / 1e6, 2),
            "overlap": stage not in _TILING and stage != STAGE_CLIENT,
        }

    wall_ns = sum(client_wall.values())
    covered_ns = sum(covered.get(tid, 0) for tid in client_wall)
    return {
        "traced_requests": len(client_wall),
        "wall_ms_total": round(wall_ns / 1e6, 2),
        "covered_ms_total": round(covered_ns / 1e6, 2),
        "coverage": round(covered_ns / wall_ns, 3) if wall_ns else 0.0,
        "stages": stages,
    }


def to_chrome_trace(records) -> dict:
    """Chrome trace-event JSON object (Perfetto-loadable).

    One complete event per span; per-request spans land on a track (tid)
    per trace id so a request's stages read as one lane, process-level
    spans (trace id 0) on track 0.  Every event carries the recording
    process id and — when the runtime runs sharded event loops
    (raft.tpu.server.loop-shards) — the origin loop thread, compressed to
    a small per-process shard ordinal, so a cross-shard/cross-process
    merge stays attributable."""
    import os
    pid = os.getpid()
    events = []
    shard_of: dict[int, int] = {}
    for rec in records:
        tid, stage, t0, dur, tag = rec[0], rec[1], rec[2], rec[3], rec[4]
        origin = rec[5] if len(rec) > 5 else 0
        shard = shard_of.setdefault(origin, len(shard_of)) if origin else 0
        events.append({
            "name": STAGE_NAMES[stage],
            "cat": "hostpath",
            "ph": "X",
            "ts": t0 / 1e3,         # microseconds since monotonic epoch
            "dur": max(dur, 1) / 1e3,
            "pid": pid,
            "tid": tid,
            "args": {"trace_id": tid, "tag": tag, "loop_shard": shard},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, records) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(records), f)
    return path


def merge_chrome_traces(traces: "list[dict]") -> dict:
    """Fold per-process Chrome trace exports into ONE cluster trace.

    Every event already carries the recording process id (``pid``), so a
    merge is a concatenation: Perfetto renders one process group per pid
    with that process's per-request tracks inside it.  Malformed inputs
    (a child that crashed mid-write) contribute nothing rather than
    poisoning the merged artifact."""
    events: list = []
    for trace in traces:
        if isinstance(trace, dict):
            evs = trace.get("traceEvents")
            if isinstance(evs, list):
                events.extend(evs)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_chrome_trace_files(paths: "list[str]", out_path: str) -> dict:
    """Read per-process trace files (skipping unreadable ones), merge,
    write the cluster trace to ``out_path``, and return the merged dict."""
    traces = []
    for path in paths:
        try:
            with open(path) as f:
                traces.append(json.load(f))
        except (OSError, ValueError):
            continue
    merged = merge_chrome_traces(traces)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return merged
