"""Host-path tracing subsystem: request->commit spans, stage decomposition,
Perfetto export.  See :mod:`ratis_tpu.trace.tracer` for the recording model
and :mod:`ratis_tpu.trace.export` for aggregation/export."""

from ratis_tpu.trace.tracer import (NUM_STAGES, STAGE_APPEND, STAGE_APPLY,
                                    STAGE_CLIENT, STAGE_DECODE, STAGE_ENCODE,
                                    STAGE_ENGINE, STAGE_NAMES, STAGE_REPLICATE,
                                    STAGE_ROUTE, STAGE_TXN, STAGE_WIRE,
                                    TILING_STAGES, TRACER, SpanRing, Tracer,
                                    configure_from_properties, get_tracer)

__all__ = [
    "NUM_STAGES", "STAGE_APPEND", "STAGE_APPLY", "STAGE_CLIENT",
    "STAGE_DECODE", "STAGE_ENCODE", "STAGE_ENGINE", "STAGE_NAMES",
    "STAGE_REPLICATE", "STAGE_ROUTE", "STAGE_TXN", "STAGE_WIRE",
    "TILING_STAGES", "TRACER", "SpanRing", "Tracer",
    "configure_from_properties", "get_tracer",
]
