"""CounterStateMachine: the minimal demo/test state machine.

Capability parity with the reference counter example
(ratis-examples/.../counter/server/CounterStateMachine.java:63):
INCREMENT via applyTransaction (:263), GET via query (:234), snapshot as the
serialized counter (takeSnapshot:160).
"""

from __future__ import annotations

import struct

from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX
from ratis_tpu.server.statemachine import (SnapshotInfo, StateMachine,
                                           TransactionContext)

INCREMENT = b"INCREMENT"
GET = b"GET"


class CounterStateMachine(StateMachine):
    def __init__(self):
        super().__init__()
        self.counter = 0

    async def start_transaction(self, request) -> TransactionContext:
        if request.message.content != INCREMENT:
            trx = TransactionContext(client_request=request)
            trx.exception = ValueError(
                f"invalid command {request.message.content!r}; "
                f"only {INCREMENT!r} is a write")
            return trx
        return TransactionContext(client_request=request,
                                  log_data=request.message.content)

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        self.counter += 1
        e = trx.log_entry
        if e is not None:
            self.update_last_applied_term_index(e.term, e.index)
        return Message.value_of(str(self.counter))

    async def query(self, request: Message) -> Message:
        if request.content != GET:
            raise ValueError(f"invalid query {request.content!r}")
        return Message.value_of(str(self.counter))

    async def take_snapshot(self) -> int:
        ti = self.get_last_applied_term_index()
        if ti.index == INVALID_LOG_INDEX:
            return INVALID_LOG_INDEX
        path = self._storage.snapshot_path(ti.term, ti.index)
        path.write_bytes(struct.pack(">q", self.counter))
        return ti.index

    async def restore_from_snapshot(self, snapshot: SnapshotInfo) -> None:
        import pathlib
        path = pathlib.Path(snapshot.files[0].path)
        (self.counter,) = struct.unpack(">q", path.read_bytes())
