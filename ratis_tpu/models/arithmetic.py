"""Arithmetic state machine: replicated variable map with expression eval.

Capability parity with the reference arithmetic example
(ratis-examples/src/main/java/org/apache/ratis/examples/arithmetic/
ArithmeticStateMachine.java): transactions assign ``var = expression``
where the expression may reference previously assigned variables; queries
evaluate a variable (or expression) against the current map.  Expressions
are parsed with :mod:`ast` restricted to arithmetic nodes — never ``eval``.
Snapshot = the whole variable map (reference serializes the map the same
way).
"""

from __future__ import annotations

import ast
import asyncio
import math
import operator
import msgpack
from typing import Dict

from ratis_tpu.protocol.message import Message
from ratis_tpu.server.statemachine import (BaseStateMachine,
                                           TransactionContext)

_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
}
_UNARYOPS = {ast.USub: operator.neg, ast.UAdd: operator.pos}
_FUNCS = {"sqrt": math.sqrt}


def _encode_value(v):
    if isinstance(v, complex):
        return {"__complex__": [v.real, v.imag]}
    raise TypeError(f"unserializable snapshot value {v!r}")


def _decode_value(v):
    if isinstance(v, dict) and "__complex__" in v:
        re_, im = v["__complex__"]
        return complex(re_, im)
    return v


def evaluate(expression: str, variables: Dict[str, float]) -> float:
    """Safely evaluate an arithmetic expression over the variable map."""
    tree = ast.parse(expression, mode="eval")

    def _eval(node: ast.AST) -> float:
        if isinstance(node, ast.Expression):
            return _eval(node.body)
        if isinstance(node, ast.Constant):
            if not isinstance(node.value, (int, float)):
                raise ValueError(f"non-numeric constant {node.value!r}")
            return float(node.value)
        if isinstance(node, ast.Name):
            if node.id not in variables:
                raise ValueError(f"undefined variable {node.id!r}")
            return variables[node.id]
        if isinstance(node, ast.BinOp) and type(node.op) in _BINOPS:
            return _BINOPS[type(node.op)](_eval(node.left), _eval(node.right))
        if isinstance(node, ast.UnaryOp) and type(node.op) in _UNARYOPS:
            return _UNARYOPS[type(node.op)](_eval(node.operand))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _FUNCS and len(node.args) == 1 \
                and not node.keywords:
            return _FUNCS[node.func.id](_eval(node.args[0]))
        raise ValueError(f"disallowed expression node {type(node).__name__}")

    return _eval(tree)


class ArithmeticStateMachine(BaseStateMachine):
    """Transactions: ``b"x = y + 1"``; queries: ``b"x"`` (any expression)."""

    def __init__(self) -> None:
        super().__init__()
        self.variables: Dict[str, float] = {}

    async def start_transaction(self, request) -> TransactionContext:
        """Reject malformed assignments before they consume a log entry
        (counter/filestore pattern).  Only syntax is checked — variable
        existence depends on entries still in flight, so name resolution
        stays at apply time."""
        trx = TransactionContext(client_request=request,
                                 log_data=request.message.content)
        try:
            var, _, expression = request.message.content.decode().partition("=")
            if not var.strip().isidentifier():
                raise ValueError(
                    f"invalid assignment target {var.strip()!r}")
            ast.parse(expression.strip(), mode="eval")
        except Exception as e:
            trx.exception = e
        return trx

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        e = trx.log_entry
        assignment = (e.smlog.log_data if e is not None and e.smlog is not None
                      else (trx.log_data or b"")).decode()
        var, _, expression = assignment.partition("=")
        var = var.strip()
        if not var.isidentifier():
            raise ValueError(f"invalid assignment target {var!r}")
        value = evaluate(expression.strip(), self.variables)
        self.variables[var] = value
        if e is not None:
            self.update_last_applied_term_index(e.term, e.index)
        return Message.value_of(repr(value))

    async def query(self, request: Message) -> Message:
        value = evaluate(request.content.decode().strip(), self.variables)
        return Message.value_of(repr(value))

    async def query_stale(self, request: Message, min_index: int) -> Message:
        return await self.query(request)

    async def take_snapshot(self) -> int:
        ti = self.get_last_applied_term_index()
        if ti.index < 0:
            return -1
        storage = self.get_state_machine_storage()
        if storage.directory is None:
            return -1  # volatile group: nothing durable to snapshot to
        path = storage.snapshot_path(ti.term, ti.index)
        # msgpack, not pickle: snapshot files can be installed over the
        # network from another peer, so the format must not execute code.
        # evaluate() can yield complex (e.g. (-2) ** 0.5) — tag those.
        data = msgpack.packb(dict(self.variables), use_bin_type=True,
                             default=_encode_value)
        await asyncio.to_thread(self._write_snapshot, path, data)
        return ti.index

    @staticmethod
    def _write_snapshot(path, data: bytes) -> None:
        tmp = path.with_suffix(".tmp")
        tmp.write_bytes(data)
        tmp.replace(path)

    async def restore_from_snapshot(self, snapshot) -> None:
        if snapshot is None or not snapshot.files:
            return
        import pathlib
        data = pathlib.Path(snapshot.files[0].path).read_bytes()
        try:
            raw = msgpack.unpackb(data, raw=False, strict_map_key=False)
        except Exception as e:
            raise ValueError(
                "arithmetic snapshot is not msgpack (unsupported legacy "
                "format?): " + str(e)) from e
        self.variables = {k: _decode_value(v) for k, v in raw.items()}
        self.set_last_applied_term_index(snapshot.term_index)
