"""FileStore: a replicated file service exercising the DataStream path.

Capability parity with the reference filestore example
(ratis-examples/src/main/java/org/apache/ratis/examples/filestore/
FileStoreStateMachine.java:48 + FileStore.java): small files ride the raft
log as WRITE transactions; large files stream peer-to-peer over the
DataStream path (``stream``:196 opens a channel into a temp file,
``link``:210 renames it into place when the raft entry commits).  Queries
read file bytes / list the store.

Commands (msgpack dicts in the Message body):
  write  {op, path, data}     — file content through the log
  stream {op, path, size}     — DataStream header; bytes arrive out of band
  delete {op, path}
  read   {op, path} (query)   — file bytes
  list   {op} (query)         — sorted file names
"""

from __future__ import annotations

import asyncio
import os
import pathlib
import tempfile
from typing import Dict, Optional

import msgpack

from ratis_tpu.protocol.message import Message
from ratis_tpu.server.statemachine import (BaseStateMachine, DataChannel,
                                           DataStream, TransactionContext)


def _safe_relpath(path: str) -> pathlib.PurePosixPath:
    p = pathlib.PurePosixPath(path)
    if p.is_absolute() or ".." in p.parts or not p.parts:
        raise ValueError(f"unsafe path {path!r}")
    return p


class FileChunkChannel(DataChannel):
    """Streams into ``<root>/.tmp/<stream>``; linked (renamed) at apply."""

    def __init__(self, tmp_path: pathlib.Path) -> None:
        self.tmp_path = tmp_path
        self._file = open(tmp_path, "wb")

    async def write(self, data: bytes) -> int:
        return await asyncio.to_thread(self._file.write, data)

    async def force(self, metadata: bool = False) -> None:
        def _sync():
            self._file.flush()
            os.fsync(self._file.fileno())
        await asyncio.to_thread(_sync)

    async def close(self) -> None:
        if not self._file.closed:
            await asyncio.to_thread(self._file.close)


class FileStoreDataStream(DataStream):
    def __init__(self, channel: FileChunkChannel, request,
                 target: pathlib.PurePosixPath) -> None:
        super().__init__(channel, request)
        self.target = target

    async def cleanup(self) -> None:
        await self.channel.close()
        self.channel.tmp_path.unlink(missing_ok=True)


class FileStoreStateMachine(BaseStateMachine):
    def __init__(self, root: Optional[str] = None) -> None:
        super().__init__()
        self._explicit_root = root
        self._root: Optional[pathlib.Path] = None
        self._tmp_holder: Optional[tempfile.TemporaryDirectory] = None
        self.files: Dict[str, int] = {}  # path -> size (committed metadata)
        self._stream_seq = 0

    # ------------------------------------------------------------- layout

    @property
    def root(self) -> pathlib.Path:
        if self._root is None:
            if self._explicit_root is not None:
                self._root = pathlib.Path(self._explicit_root)
            elif self._storage.directory is not None:
                self._root = self._storage.directory / "files"
            else:  # volatile group: keep files in a temp dir for our lifetime
                self._tmp_holder = tempfile.TemporaryDirectory(
                    prefix="filestore-")
                self._root = pathlib.Path(self._tmp_holder.name)
            (self._root / ".tmp").mkdir(parents=True, exist_ok=True)
        return self._root

    def resolve(self, path: str) -> pathlib.Path:
        return self.root / _safe_relpath(path)

    async def close(self) -> None:
        if self._tmp_holder is not None:
            self._tmp_holder.cleanup()
        await super().close()

    # ----------------------------------------------------------- pipeline

    async def start_transaction(self, request) -> TransactionContext:
        trx = TransactionContext(client_request=request,
                                 log_data=request.message.content)
        try:
            cmd = msgpack.unpackb(request.message.content, raw=False)
            op = cmd["op"]
            if op not in ("write", "stream", "delete"):
                raise ValueError(f"not a transaction op: {op!r}")
            _safe_relpath(cmd["path"])
        except Exception as e:
            trx.exception = e
        return trx

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        e = trx.log_entry
        payload = (e.smlog.log_data if e is not None and e.smlog is not None
                   else (trx.log_data or b""))
        cmd = msgpack.unpackb(payload, raw=False)
        op, path = cmd["op"], cmd.get("path", "")
        reply: dict
        if op == "write":
            target = self.resolve(path)
            target.parent.mkdir(parents=True, exist_ok=True)
            await asyncio.to_thread(self._atomic_write, target, cmd["data"])
            self.files[path] = len(cmd["data"])
            reply = {"ok": True, "size": len(cmd["data"])}
        elif op == "stream":
            # bytes were linked into place just before apply (data_link);
            # a peer outside the routing table simply has no local copy
            target = self.resolve(path)
            if target.exists():
                size = target.stat().st_size
                self.files[path] = size
                reply = {"ok": True, "size": size}
            else:
                reply = {"ok": False, "error": "data not streamed here"}
        elif op == "delete":
            target = self.resolve(path)
            await asyncio.to_thread(target.unlink, True)
            self.files.pop(path, None)
            reply = {"ok": True}
        else:
            reply = {"ok": False, "error": f"unknown op {op!r}"}
        if e is not None:
            self.update_last_applied_term_index(e.term, e.index)
        return Message(msgpack.packb(reply, use_bin_type=True))

    @staticmethod
    def _atomic_write(target: pathlib.Path, data: bytes) -> None:
        tmp = target.with_name(target.name + ".part")
        tmp.write_bytes(data)
        tmp.replace(target)

    # -------------------------------------------------------------- query

    async def query(self, request: Message) -> Message:
        cmd = msgpack.unpackb(request.content, raw=False)
        op = cmd["op"]
        if op == "read":
            target = self.resolve(cmd["path"])
            data = await asyncio.to_thread(target.read_bytes)
            return Message(msgpack.packb({"ok": True, "data": data},
                                         use_bin_type=True))
        if op == "list":
            return Message(msgpack.packb(
                {"ok": True, "files": sorted(self.files)},
                use_bin_type=True))
        raise ValueError(f"unknown query {op!r}")

    async def query_stale(self, request: Message, min_index: int) -> Message:
        return await self.query(request)

    # ----------------------------------------------------------- DataApi

    async def data_stream(self, request) -> DataStream:
        cmd = msgpack.unpackb(request.message.content, raw=False)
        if cmd.get("op") != "stream":
            raise ValueError("datastream header must be a stream op")
        target = _safe_relpath(cmd["path"])
        self._stream_seq += 1
        tmp = self.root / ".tmp" / \
            f"stream_{request.type.stream_id}_{self._stream_seq}"
        return FileStoreDataStream(FileChunkChannel(tmp), request, target)

    async def data_link(self, stream: Optional[DataStream], entry) -> None:
        if stream is None:
            return
        await stream.channel.close()
        target = self.root / stream.target
        target.parent.mkdir(parents=True, exist_ok=True)
        await asyncio.to_thread(os.replace, stream.channel.tmp_path, target)
