"""RaftServer: the multi-Raft host (one process, many groups, one endpoint).

Capability parity with the reference RaftServerProxy
(ratis-server/.../impl/RaftServerProxy.java:81): a map of
groupId -> Division behind a single transport endpoint, group add/remove
(groupManagementAsync:490), request routing (getImpl:376), and lifecycle.
The reference's per-division thread fleet is replaced by the shared
QuorumEngine tick loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from ratis_tpu.conf.keys import RaftConfigKeys, RaftServerConfigKeys
from ratis_tpu.engine.engine import QuorumEngine
from ratis_tpu.protocol.exceptions import (AlreadyExistsException,
                                           GroupMismatchException,
                                           RaftException)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest, AppendEnvelope,
                                        AppendEnvelopeReply,
                                        InstallSnapshotRequest,
                                        ReadIndexRequest, RequestVoteRequest,
                                        StartLeaderElectionRequest)
from ratis_tpu.protocol.requests import (DEFERRED_REPLY, RaftClientReply,
                                         RaftClientRequest)
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.server.division import Division
from ratis_tpu.server.statemachine import StateMachine
from ratis_tpu.transport.base import ServerTransport, TransportFactory
from ratis_tpu.util.lifecycle import LifeCycle, LifeCycleState

LOG = logging.getLogger(__name__)

# StateMachine registry: groupId -> StateMachine instance
StateMachineRegistry = Callable[[RaftGroupId], StateMachine]


class HeartbeatScheduler:
    """ONE periodic task per server sweeping every leader division's
    appenders (replaces a heartbeat-timer task per (division, follower) —
    2G standing tasks was the multi-raft scaling wall).  Each sweep wakes
    the appender fill paths, runs slowness detection, and sends any due
    heartbeats.  With coalescing enabled the sweep collects one COMPACT
    bulk item per due appender and ships one BulkHeartbeat RPC per
    destination server (see protocol.raftrpc.BulkHeartbeat — the per-item
    cost is a few dict lookups, not a full AppendEntries build+handle);
    without it, each appender sends its own unary AppendEntries heartbeat
    (the reference's cost shape)."""

    def __init__(self, server: "RaftServer", interval_s: float,
                 shard: Optional[int] = None, service=None):
        self.server = server
        self.interval_s = interval_s
        # loop sharding: shard i's scheduler runs ON shard i's loop and
        # sweeps ONLY divisions pinned there (appender/leader state is
        # loop-affine).  None = the single-loop sweep over every division.
        self.shard = shard
        self.service = service  # BulkHeartbeatService (defaults to server's)
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._sweep_seq = 0
        # array mode (raft.tpu.upkeep.enabled): this shard's UpkeepPlane;
        # None keeps the legacy per-division walk below bit-for-bit
        self.plane = None

    def start(self) -> None:
        self._running = True
        if self.service is None:
            self.service = self.server.heartbeats
        self.plane = self.server.upkeep_plane_for(self.shard or 0)
        name = (f"heartbeats-{self.server.peer_id}" if self.shard is None
                else f"heartbeats-{self.server.peer_id}-s{self.shard}")
        self._task = asyncio.create_task(self._run(), name=name)
        self._task.add_done_callback(self._on_exit)

    def _on_exit(self, task: asyncio.Task) -> None:
        """Belt-and-braces: if the sweep task ever dies while the server is
        running (a bug the try/except in _run should make impossible),
        restart it instead of silently losing every heartbeat forever."""
        if not self._running or task.cancelled():
            return
        LOG.error("heartbeat sweep task for %s exited unexpectedly "
                  "(%s); restarting", self.server.peer_id, task.exception())
        self.start()

    async def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        import time as _time
        while self._running:
            await asyncio.sleep(self.interval_s)
            now = _time.monotonic()
            self._sweep_seq += 1
            if self.plane is not None:
                await self._sweep_plane(now)
                continue
            coalesce = self.server.heartbeat_coalescing
            # destination -> ([bulk items], [appenders], aligned)
            bulk: dict[RaftPeerId, tuple[list, list]] = {}
            sweep = 0
            for i, div in enumerate(list(self.server.divisions.values())):
                if self.shard is not None \
                        and self.server.shard_of_group(div.group_id) \
                        != self.shard:
                    continue  # another shard's scheduler owns this division
                # One division's failure must never kill the single
                # server-wide heartbeat task — that silently collapses every
                # leadership on the server with no recovery path.
                try:
                    if not div.is_leader() or div.leader_ctx is None:
                        continue
                    if (self._sweep_seq + i) % 4 == 0:
                        # priority-yield scan is O(followers) python; its
                        # urgency is seconds, so a quarter-rate phase-spread
                        # scan keeps the sweep cheap at thousands of leaders
                        div.check_yield_to_higher_priority()
                    hib = (div.hibernate_sweep(now) if coalesce
                           else "awake")
                    if hib == "asleep":
                        continue  # hibernated: the group costs nothing
                    for appender in list(div.leader_ctx.appenders.values()):
                        sweep += 1
                        if coalesce:
                            item = appender.heartbeat_item(
                                now, hibernate=(hib == "request"))
                            if item is not None:
                                b = bulk.setdefault(
                                    appender.follower.peer_id, ([], []))
                                b[0].append(item)
                                b[1].append(appender)
                        else:
                            appender.on_heartbeat_sweep(now)
                        if sweep % 1024 == 0:
                            # Yield so the sweep never stalls the loop for
                            # one giant synchronous burst — but COARSELY: on
                            # a saturated loop every yield waits out the
                            # whole ready backlog, and at 40960 items a
                            # per-256 cadence stretched the sweep past the
                            # election timeout (followers of healthy
                            # leaders heard 16s+ of silence and deposed
                            # them).  1024 items ≈ tens of ms per stretch.
                            await asyncio.sleep(0)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    LOG.exception("heartbeat sweep failed for %s",
                                  div.member_id)
            for to, (items, appenders) in bulk.items():
                self.service.submit(to, items, appenders)

    async def _sweep_plane(self, now: float) -> None:
        """Array-mode sweep: ONE vectorized due-scan over the shard's
        packed deadlines, then the SAME per-division body as the legacy
        walk — but only for the due slots.  Non-leader and asleep groups
        hold +inf deadlines and cost nothing here."""
        from ratis_tpu.ops.upkeep import (CH_CACHE, CH_HEARTBEAT,
                                          CH_HIBERNATE, CH_WATCH, CH_WINDOW)
        plane = self.plane
        resync = self.server.upkeep_resync_sweeps
        if resync and self._sweep_seq % resync == 0:
            self._plane_resync(now)
        timer = plane._timer
        ctx = timer.time() if timer is not None else None
        if ctx is not None:
            ctx.__enter__()
        try:
            slots, mask = plane.sweep(now)
            coalesce = self.server.heartbeat_coalescing
            bulk: dict[RaftPeerId, tuple[list, list]] = {}
            sweep = 0
            for j in range(len(slots)):
                slot = int(slots[j])
                div = plane.division_at(slot)
                if div is None:
                    continue
                gen = div.upkeep_gen
                try:
                    if mask[j, CH_WATCH]:
                        plane.clear(slot, gen, CH_WATCH)
                        div._update_watch_frontiers()
                    if mask[j, CH_CACHE]:
                        plane.set_deadline(slot, gen, CH_CACHE,
                                           div.sweep_caches(now))
                    if mask[j, CH_WINDOW]:
                        plane.set_deadline(slot, gen, CH_WINDOW,
                                           div.sweep_client_windows_due())
                    if mask[j, CH_HEARTBEAT] or mask[j, CH_HIBERNATE]:
                        sweep = await self._heartbeat_division(
                            div, slot, now, coalesce, bulk, sweep)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    LOG.exception("upkeep sweep failed for %s",
                                  div.member_id)
            for to, (items, appenders) in bulk.items():
                self.service.submit(to, items, appenders)
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    async def _heartbeat_division(self, div, slot: int, now: float,
                                  coalesce: bool, bulk: dict,
                                  sweep: int) -> int:
        """Identical body to one legacy-walk iteration, plus the
        post-dispatch re-arm (``Division.upkeep_rearm_heartbeat``)."""
        if not div.is_leader() or div.leader_ctx is None:
            div.upkeep_rearm_heartbeat(now)  # clears the leader channels
            return sweep
        if (self._sweep_seq + slot) % 4 == 0:
            # same quarter-rate phase spread as the legacy walk (slot is
            # as stable an offset as the enumeration index was)
            div.check_yield_to_higher_priority()
        hib = div.hibernate_sweep(now) if coalesce else "awake"
        if hib != "asleep":
            for appender in list(div.leader_ctx.appenders.values()):
                sweep += 1
                if coalesce:
                    item = appender.heartbeat_item(
                        now, hibernate=(hib == "request"))
                    if item is not None:
                        b = bulk.setdefault(
                            appender.follower.peer_id, ([], []))
                        b[0].append(item)
                        b[1].append(appender)
                else:
                    appender.on_heartbeat_sweep(now)
                if sweep % 1024 == 0:
                    # same coarse yield discipline as the legacy walk
                    await asyncio.sleep(0)
        div.upkeep_rearm_heartbeat(now)
        return sweep

    def _plane_resync(self, now: float) -> None:
        """Low-rate O(G) backstop against a missed re-arm hook: re-derive
        every registered division's deadlines from current state.  At the
        default 64-sweep cadence (~5s) the amortized cost is negligible;
        the hooks alone are believed sufficient — this bounds the blast
        radius of being wrong to one resync period."""
        plane = self.plane
        for div in plane._divisions:  # hot-loop-gate: allowlisted resync
            if div is None:
                continue
            div.upkeep_rearm_heartbeat(now)
            div.upkeep_arm_cache(now)
            div.upkeep_arm_window()


class BulkHeartbeatService:
    """Sends one BulkHeartbeat per destination server per sweep and routes
    the aligned per-item replies back to their appenders.  A failed send is
    simply dropped — the next sweep retries, and persistent failure
    surfaces through leadership staleness (no acks) exactly like a dead
    unary heartbeat channel would."""

    # One BulkHeartbeat RPC carries at most this many group items: a
    # 10k-item bulk is O(all co-hosted groups) handling time inside ONE
    # rpc deadline — measured at 5-peer x 10240 groups, the whole bulk
    # blew the rpc timeout, every ack was lost at once, and the staleness
    # sweep deposed thousands of healthy leaders.  Chunks fail (and
    # retry) independently.
    MAX_ITEMS_PER_RPC = 2048

    def __init__(self, server: "RaftServer"):
        self.server = server
        self.metrics = {"batches": 0, "heartbeats": 0}
        self._pending: set[asyncio.Task] = set()

    def submit(self, to: RaftPeerId, items: list, appenders: list) -> None:
        n = self.MAX_ITEMS_PER_RPC
        for i in range(0, len(items), n):
            t = asyncio.create_task(
                self._send(to, items[i:i + n], appenders[i:i + n]))
            self._pending.add(t)
            t.add_done_callback(self._pending.discard)

    async def _send(self, to: RaftPeerId, items: list, appenders: list) -> None:
        from ratis_tpu.protocol.raftrpc import BulkHeartbeat
        self.metrics["batches"] += 1
        self.metrics["heartbeats"] += len(items)
        try:
            reply = await self.server.send_server_rpc(
                to, BulkHeartbeat(self.server.peer_id, to, tuple(items)))
        except asyncio.CancelledError:
            raise
        except Exception:
            # No send-clock rollback needed: the sweep period equals the
            # heartbeat interval and the due check is 0.9x interval, so a
            # failed item re-qualifies at the very next sweep anyway — the
            # failure costs at most one sweep period, never a silent extra
            # interval (unary mode routes the same failure through
            # on_send_error for its backoff semantics).
            return
        if len(reply.items) != len(items):
            LOG.warning("%s: bulk heartbeat reply misaligned from %s",
                        self.server.peer_id, to)
            return  # items re-qualify next sweep (see send-failure note)
        # packed ack intake (sweep mode): the whole bulk's heartbeat acks
        # enter the engine as one on_ack_batch instead of one scalar
        # on_ack (and one intake-lock round-trip) per item
        ack_rows = ([] if getattr(self.server, "replication_sweep", False)
                    else None)
        for appender, item in zip(appenders, reply.items):
            try:
                await appender.on_bulk_reply(*item, ack_sink=ack_rows)
            except asyncio.CancelledError:
                raise
            except Exception:
                LOG.exception("%s bulk heartbeat reply dispatch failed",
                              self.server.peer_id)
        if ack_rows:
            self.server.engine.on_ack_batch(ack_rows)

    async def close(self) -> None:
        for task in list(self._pending):
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._pending.clear()


class _LaneGap(Exception):
    """A buffered frame's lane gap never filled (its predecessor frame was
    lost): reject the frame with a rewind hint instead of processing it."""


class _LaneIntake:
    """Follower-side state of ONE sequenced append lane (RaftServer lane
    intake): frames process strictly in sequence — ``next_process`` only
    advances when a frame's processing COMPLETES, so a group's items in
    frame k+1 can never reach its division before frame k's (the ordering
    the sender's busy latch used to provide).  Out-of-order arrivals park
    on per-seq futures.  The ``busy`` flag is an OWNERSHIP token: a
    completing frame hands it directly to its parked successor
    (``pass_on`` wakes the future with busy left True), so the lane is
    never observably idle between back-to-back frames — which is also
    what keeps the gap timer honest: a genuine sequence gap (the frame we
    need next never arrived while the lane is idle) is detected by a
    one-shot timer and rejects every parked frame with a rewind hint."""

    # how long a parked frame waits for a missing predecessor before the
    # lane rejects it (a merely-slow predecessor never trips this — the
    # timer only fires when the needed frame never ARRIVED)
    GAP_WAIT_S = 1.0

    __slots__ = ("next_process", "next_arrival", "busy", "waiting",
                 "gap_timer", "last_used")

    def __init__(self, first_seq: int):
        # adopt the first observed sequence: a receiver restart (or lane
        # eviction) must not reject a healthy lane forever
        self.next_process = first_seq
        self.next_arrival = first_seq
        self.busy = False
        self.waiting: dict[int, asyncio.Future] = {}
        self.gap_timer = None
        self.last_used = 0.0

    @property
    def gapped(self) -> bool:
        """Frames are parked but the one we need next never arrived."""
        return (not self.busy and bool(self.waiting)
                and self.next_process not in self.waiting)

    def arm_gap_timer(self, loop: asyncio.AbstractEventLoop) -> None:
        if self.gap_timer is None and self.gapped:
            self.gap_timer = loop.call_later(self.GAP_WAIT_S,
                                             self._on_gap_timer)

    def _on_gap_timer(self) -> None:
        self.gap_timer = None
        if not self.gapped:
            return
        for fut in self.waiting.values():
            if not fut.done():
                fut.set_exception(_LaneGap())
        self.waiting.clear()

    def pass_on(self, loop: asyncio.AbstractEventLoop) -> None:
        """Release lane ownership: hand it to the parked ``next_process``
        frame (busy stays True across the transfer), or mark the lane
        idle and (re-)arm gap detection if later frames wait on a hole."""
        fut = self.waiting.pop(self.next_process, None)
        if fut is not None and not fut.done():
            fut.set_result(None)  # ownership transferred
        else:
            self.busy = False
            self.arm_gap_timer(loop)

    def close(self) -> None:
        if self.gap_timer is not None:
            self.gap_timer.cancel()
            self.gap_timer = None
        for fut in self.waiting.values():
            if not fut.done():
                fut.set_exception(_LaneGap())
        self.waiting.clear()


class RaftServer:
    def __init__(self, peer_id: RaftPeerId, address: str,
                 state_machine_registry: StateMachineRegistry,
                 properties, transport_factory: TransportFactory,
                 group: Optional[RaftGroup] = None,
                 log_factory: Optional[Callable] = None):
        self.peer_id = peer_id
        self.address = address
        self.properties = properties
        # Host-path tracing (ratis_tpu.trace): enables the process-wide
        # tracer when raft.tpu.trace.enabled is set; a no-op otherwise.
        from ratis_tpu.trace import configure_from_properties
        configure_from_properties(properties)
        self._sm_registry = state_machine_registry
        self._initial_group = group
        self._log_factory = log_factory
        self._transport_factory = transport_factory
        self.life_cycle = LifeCycle(f"server-{peer_id}")
        self.divisions: dict[RaftGroupId, Division] = {}
        # Shared log plane (raft.tpu.log.shared): one interleaved store per
        # loop shard, created on first use, refcounted by its divisions.
        self._shared_log_stores: dict[int, object] = {}
        # Loop sharding (raft.tpu.server.loop-shards): N worker event loops
        # with every Division hash-pinned to one; None (shards=1, the
        # default) keeps the single-loop runtime with zero indirection.
        self.loop_shards = RaftServerConfigKeys.loop_shards(properties)
        self.shards = None
        if self.loop_shards > 1:
            from ratis_tpu.server.shards import LoopShardPool
            self.shards = LoopShardPool(f"{peer_id}", self.loop_shards)
        # Transaction contexts between append and apply
        # (reference TransactionManager, ratis-server/.../impl/).
        self.transactions: dict = {}

        p = properties
        mesh = None
        mesh_n = RaftServerConfigKeys.Engine.mesh_devices(p)
        if mesh_n > 0:
            # Multi-chip deployment: shard the resident engine state over
            # the group axis of an n-device mesh (ratis_tpu.parallel.mesh;
            # the row-local quorum math keeps the step collective-free).
            from ratis_tpu.parallel import make_group_mesh
            mesh = make_group_mesh(mesh_n)
        self.engine = QuorumEngine(
            max_groups=RaftServerConfigKeys.Engine.max_groups(p),
            max_peers=RaftServerConfigKeys.Engine.max_peers(p),
            tick_interval_s=RaftServerConfigKeys.Engine.tick_interval(p).seconds,
            scalar_fallback_threshold=p.get_int(
                RaftServerConfigKeys.Engine.SCALAR_FALLBACK_THRESHOLD_KEY,
                RaftServerConfigKeys.Engine.SCALAR_FALLBACK_THRESHOLD_DEFAULT),
            leadership_timeout_ms=int(
                RaftServerConfigKeys.Rpc.timeout_max(p).to_ms() * 2),
            mesh=mesh,
            profile_dir=RaftServerConfigKeys.Engine.profile_dir(p) or None,
            name=str(peer_id))
        # lag & health ledger thresholds (raft.tpu.lag.*); the ledger
        # itself is part of the engine
        self.engine.ledger.lag_threshold = RaftServerConfigKeys.Lag.threshold(p)
        self.engine.ledger.up_window_ms = int(
            RaftServerConfigKeys.Lag.up_window(p).to_ms())
        self.lag_top_groups = RaftServerConfigKeys.Lag.top_groups(p)
        self.pause_monitor = None  # started in start() when enabled
        # Observability plane (raft.tpu.metrics.http-port /
        # raft.tpu.watchdog.*): the per-server introspection endpoint and
        # the stall watchdog, both created in start().  With the port key
        # unset no listener socket is ever opened.
        self.metrics_http = None
        self.watchdog = None
        # Continuous telemetry (raft.tpu.telemetry.*): the background
        # time-series sampler + flight recorder, created in start() only
        # when enabled — off is zero-cost, identical paths.
        self.telemetry = None
        self.flight = None
        # Placement controller (raft.tpu.placement.enabled): the opt-in
        # telemetry-driven rebalancing loop, created in start() — unset
        # keeps every request/read path bit-identical to a build without
        # the subsystem.
        self.placement = None
        from ratis_tpu.conf.reconfiguration import ReconfigurationManager
        # live property reconfiguration (divisions register their knobs)
        self.reconfiguration = ReconfigurationManager(properties)
        self.heartbeats = BulkHeartbeatService(self)
        self.heartbeat_coalescing = \
            RaftServerConfigKeys.Heartbeat.coalescing_enabled(p)
        # Data-path fan-out: one PeerSender per destination server drains
        # every group's append batches (ratis_tpu.server.replication).
        # The sweep discipline (raft.tpu.replication.*) batches the whole
        # replication plane: cross-group append sweeps per (destination,
        # loop-shard), packed ack intake (engine.on_ack_batch), and the
        # commit fan-out collapse; sweep=0 keeps the per-request paths.
        from ratis_tpu.server.replication import ReplicationScheduler
        appender_keys = RaftServerConfigKeys.Log.Appender
        repl_keys = RaftServerConfigKeys.Replication
        self.replication_sweep = repl_keys.sweep(p)
        self.reply_fanout = (self.replication_sweep
                             and repl_keys.reply_fanout(p))
        self.stream_shards = repl_keys.stream_shards(p)
        self.replication = ReplicationScheduler(
            self,
            coalescing=appender_keys.coalescing_enabled(p),
            inflight_cap=appender_keys.envelope_inflight(p),
            envelope_byte_limit=appender_keys.envelope_byte_limit(p),
            sweep=self.replication_sweep,
            window_depth=repl_keys.window_depth(p))
        # Follower-side sequenced lane intake
        # (raft.tpu.replication.window-depth > 1 senders): lane id ->
        # _LaneIntake processing that lane's frames strictly in sequence.
        # Bounded: dead lanes (sender restarts/re-cuts) age out by LRU.
        self._lanes: dict = {}
        self.reorder_buffer = repl_keys.reorder_buffer(p)
        self.lane_metrics = {"ooo_buffered": 0, "lane_rejects": 0,
                             "lane_frames": 0}
        # cross-frame per-group order chains (sequenced frames only):
        # group id -> the tail frame's completion future, each entry only
        # ever touched from the group's owning loop
        self._group_chains: dict = {}
        # scheduling-hops-per-commit: the fan-out collapse as a standing
        # measured artifact (metrics/hops.py; per-site gauges + the
        # hops-per-commit ratio on this server's registry)
        from ratis_tpu.metrics import hops as hops_mod
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo, labeled)
        self._plane_info = MetricRegistryInfo(
            prefix=str(peer_id), application="ratis", component="server",
            name="replication_plane")
        plane = MetricRegistries.global_registries().create(self._plane_info)
        for site in hops_mod.HOP_SITES:
            plane.gauge(labeled("schedulingHops", site=site),
                        lambda s=site: hops_mod.snapshot()[s])
        plane.gauge("replyHopsPerCommit", self.reply_hops_per_commit)
        # Window state (round 9): sender-side rewind/lane counters +
        # follower-side lane-intake counters, plus per-destination
        # frames-in-flight / occupancy gauges registered as destinations
        # appear (peers are few even when groups are many).
        rm = self.replication.metrics
        plane.gauge("windowDepth",
                    lambda: self.replication.window_depth)
        plane.gauge("windowRewinds",
                    lambda: rm.get("windowed_rewinds", 0))
        plane.gauge("windowLaneResets", lambda: rm.get("lane_resets", 0))
        plane.gauge("windowLaneRejects", lambda: rm.get("lane_rejects", 0))
        plane.gauge("laneOutOfOrderBuffered",
                    lambda: self.lane_metrics["ooo_buffered"])
        plane.gauge("laneIntakeRejects",
                    lambda: self.lane_metrics["lane_rejects"])

        def _register_window_gauges(dest) -> None:
            plane.gauge(labeled("windowFramesInFlight", dest=str(dest)),
                        lambda d=dest: self.replication.frames_in_flight(d))
            plane.gauge(labeled("windowOccupancy", dest=str(dest)),
                        lambda d=dest: self.replication.window_occupancy(d))

        self.replication.on_destination = _register_window_gauges
        # Serving plane (ratis_tpu.server.serving): intake admission
        # control + the batched readIndex scheduler, raft.tpu.serving.*.
        from ratis_tpu.server.serving import ServingPlane
        self.serving = ServingPlane(self)
        # readIndex steering table (server/read.py): always constructed
        # (an empty table is a free set() check in the sweep); only the
        # placement actuator ever populates it.
        from ratis_tpu.server.read import ReadSteering
        self.read_steering = ReadSteering()
        # Vectorized upkeep plane (raft.tpu.upkeep.*): per-loop-shard
        # packed deadline arrays replace the per-group sweep walk.  Unset
        # keeps self.upkeep empty and every caller on the legacy paths.
        self.upkeep: list = []
        self.upkeep_resync_sweeps = RaftServerConfigKeys.Upkeep \
            .resync_sweeps(p)
        self._upkeep_info = None
        if RaftServerConfigKeys.Upkeep.enabled(p):
            from ratis_tpu.server.upkeep import create_planes
            self.upkeep = create_planes(self)
            self._upkeep_info = MetricRegistryInfo(
                prefix=str(peer_id), application="ratis",
                component="server", name="upkeep_plane")
            ureg = MetricRegistries.global_registries().create(
                self._upkeep_info)
            sweep_timer = ureg.timer("upkeepSweepCost")
            idle_skips = ureg.counter("upkeepIdleSkips")
            for pl in self.upkeep:
                pl._timer = sweep_timer
                pl._idle_counter = idle_skips
            ureg.gauge("upkeepDueGroups",
                       lambda: sum(pl.last_due for pl in self.upkeep))
            ureg.gauge("upkeepRegisteredSlots",
                       lambda: sum(pl.registered for pl in self.upkeep))
        # single source of truth for the heartbeat cadence (LeaderContext
        # and the sweep must agree, or heartbeat gaps silently grow)
        self.heartbeat_interval_s = \
            RaftServerConfigKeys.Rpc.timeout_min(p).seconds / 2
        self.heartbeat_scheduler = HeartbeatScheduler(
            self, self.heartbeat_interval_s)
        # sharded mode: one (scheduler, bulk service) pair per shard, each
        # living on its shard's loop (built in start(); the unsharded
        # fields above stay exactly the single-loop runtime)
        self._hb_shards: list[HeartbeatScheduler] = []
        # peer id -> network address, fed from every conf the server sees
        # (division conf syncs, staging, group adds); the resolver transports
        # dial by (reference PeerProxyMap's address source).
        self.peer_addresses: dict[RaftPeerId, str] = {}
        if group is not None:
            for peer in group.peers:
                if peer.address:
                    self.peer_addresses[peer.id] = peer.address
        self.transport: ServerTransport = transport_factory.new_server_transport(
            peer_id, address, self._handle_server_rpc,
            self._handle_client_request, properties,
            peer_resolver=self.resolve_peer_address)

        # DataStream bulk path (reference DataStreamServerImpl; served on the
        # peer's dedicated datastream address when one is configured).  Also
        # created lazily by _add_division for groups that arrive via
        # group_add after startup.
        self.datastream = None
        self._datastream_started = False
        self._gc_disciplined = False
        self._gc_task: Optional[asyncio.Task] = None
        if group is not None:
            self._maybe_create_datastream(group)

    # ------------------------------------------------------------- lifecycle

    def _storage_root(self) -> Optional[str]:
        """Durable mode unless raft.server.log.use.memory is set.  The peer id
        becomes a path component so multiple peers sharing one machine (or the
        default dir) never collide on locks or boot-scan-adopt each other's
        group state."""
        if RaftServerConfigKeys.Log.use_memory(self.properties):
            return None
        dirs = RaftServerConfigKeys.storage_dirs(self.properties)
        if not dirs:
            return None
        return f"{dirs[0]}/{self.peer_id}"

    async def start(self) -> None:
        self.life_cycle.transition(LifeCycleState.STARTING)
        if self.shards is not None:
            # before anything that places a division: boot-scan recovery and
            # the initial group below pin divisions to shard loops
            self.shards.start()
        await self.engine.start()
        from ratis_tpu.conf.keys import RaftServerConfigKeys as _K
        if _K.Gc.discipline(self.properties):
            # Heap discipline (util.gcdiscipline): tuned thresholds now, one
            # deliberate collect+freeze once the group set settles — instead
            # of the collector's own 52s-at-10k-groups pause mid-consensus.
            from ratis_tpu.util import gcdiscipline
            gcdiscipline.enable()
            self._gc_disciplined = True
            self._gc_task = asyncio.create_task(
                self._gc_janitor(
                    _K.Gc.freeze_idle(self.properties).seconds,
                    _K.Gc.refreeze_interval(self.properties).seconds),
                name=f"gc-janitor-{self.peer_id}")
        if _K.PauseMonitor.enabled(self.properties):
            from ratis_tpu.server.pause_monitor import PauseMonitor
            self.pause_monitor = PauseMonitor(self)
            self.pause_monitor.start()
        if _K.Watchdog.enabled(self.properties):
            from ratis_tpu.server.watchdog import StallWatchdog
            self.watchdog = StallWatchdog(self)
            self.watchdog.start()
        json_routes = {"/health": self.health_info,
                       "/divisions": self.divisions_info,
                       "/events": self.watchdog_events,
                       "/lag": self.lag_info}
        if _K.Telemetry.enabled(self.properties):
            from ratis_tpu.metrics.flight import (FlightRecorder,
                                                  install_sigterm_dump)
            from ratis_tpu.metrics.timeseries import TelemetrySampler
            self.telemetry = TelemetrySampler(self)
            self.telemetry.start()
            flight_dir = _K.Telemetry.flight_dir(self.properties)
            self.flight = FlightRecorder(self, self.telemetry,
                                         dump_dir=flight_dir)
            if self.watchdog is not None:
                # organic degradation -> one debounced flight dump
                self.watchdog.on_event = self.flight.on_watchdog_event
            if flight_dir:
                install_sigterm_dump(self.flight)
            json_routes["/timeseries"] = self.telemetry.timeseries_info
            json_routes["/hotgroups"] = self.telemetry.hotgroups_info
            json_routes["/flightrecorder"] = \
                self.flight.flightrecorder_info
        if _K.Placement.enabled(self.properties):
            from ratis_tpu.placement import PlacementController
            self.placement = PlacementController(self)
            self.placement.start()
            json_routes["/placement"] = self.placement.placement_info
        http_port = _K.Metrics.http_port(self.properties)
        if http_port is not None:
            from ratis_tpu.metrics.prometheus import MetricsHttpServer
            self.metrics_http = MetricsHttpServer(
                port=http_port, json_routes=json_routes)
            await self.metrics_http.start()
        if self.shards is None:
            self.heartbeat_scheduler.start()
        else:
            # one sweep per shard, each ON its shard's loop over only its
            # own divisions (appender state is loop-affine), each with its
            # own bulk service so reply dispatch stays on-shard
            for i in range(self.shards.n):
                svc = BulkHeartbeatService(self)
                sched = HeartbeatScheduler(self, self.heartbeat_interval_s,
                                           shard=i, service=svc)
                self._hb_shards.append(sched)
                self.shards.call_soon(i, sched.start)
        # Boot scan: recover every group found on disk
        # (reference RaftServerProxy.initGroups:257-288).
        root = self._storage_root()
        if root is not None:
            from ratis_tpu.server.storage import (RaftStorageDirectory,
                                                  scan_group_dirs)
            from ratis_tpu.server.config import RaftConfiguration
            for gid in scan_group_dirs(root):
                if gid in self.divisions:
                    continue
                sd = RaftStorageDirectory(root, gid)
                conf_entry = sd.load_conf_entry()
                if conf_entry is None:
                    LOG.warning("%s: storage for %s has no conf; skipping",
                                self.peer_id, gid)
                    continue
                conf = RaftConfiguration.from_entry(conf_entry)
                group = RaftGroup.value_of(gid, conf.all_peers())
                await self._add_division(group)
        if self._initial_group is not None \
                and self._initial_group.group_id not in self.divisions:
            await self._add_division(self._initial_group)
        await self.transport.start()
        if self.datastream is not None and not self._datastream_started:
            await self.datastream.start()
            self._datastream_started = True
        self.life_cycle.transition(LifeCycleState.RUNNING)

    async def close(self) -> None:
        if not self.life_cycle.compare_and_transition(
                LifeCycleState.RUNNING, LifeCycleState.CLOSING):
            if not self.life_cycle.compare_and_transition(
                    LifeCycleState.NEW, LifeCycleState.CLOSING):
                return
        if self.metrics_http is not None:
            await self.metrics_http.close()
            self.metrics_http = None
        # the placement loop goes down before the watchdog: an in-flight
        # actuation still journals its aborted pair on cancellation
        if self.placement is not None:
            await self.placement.close()
            self.placement = None
        if self.telemetry is not None:
            if self.flight is not None:
                from ratis_tpu.metrics.flight import uninstall_sigterm_dump
                uninstall_sigterm_dump(self.flight)
                self.flight = None
            await self.telemetry.close()
            self.telemetry = None
        if self.watchdog is not None:
            await self.watchdog.close()
            self.watchdog = None
        if self.pause_monitor is not None:
            await self.pause_monitor.close()
            self.pause_monitor = None
        if self._gc_task is not None:
            self._gc_task.cancel()
            try:
                await self._gc_task
            except asyncio.CancelledError:
                pass
            self._gc_task = None
        if self._gc_disciplined:
            from ratis_tpu.util import gcdiscipline
            gcdiscipline.disable()
            self._gc_disciplined = False
        if self.shards is None:
            await self.heartbeat_scheduler.close()
        else:
            for sched in self._hb_shards:
                await self.shards.run_on(sched.shard, sched.close())
        await self.transport.close()
        if self.datastream is not None:
            await self.datastream.close()
        for div in list(self.divisions.values()):
            # whole-server shutdown (StateMachine.notifyServerShutdown,
            # StateMachine.java:277; group_remove notifies per-group instead)
            try:
                await div.state_machine.notify_server_shutdown(
                    div.role_info(), True)
            except Exception:
                LOG.exception("%s notify_server_shutdown raised",
                              div.member_id)
            await self._run_on_division_loop(div.group_id, div.close())
        self.divisions.clear()
        # after divisions: a live leader appender could otherwise submit a
        # heartbeat that recreates a flusher task in a closed coalescer
        await self.heartbeats.close()
        for sched in self._hb_shards:
            if sched.service is not None:
                await self.shards.run_on(sched.shard, sched.service.close())
        self._hb_shards.clear()
        await self.replication.close()
        for st in self._lanes.values():
            st.close()  # cancel gap timers, release any parked frames
        self._lanes.clear()
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self._plane_info)
        if self._upkeep_info is not None:
            MetricRegistries.global_registries().remove(self._upkeep_info)
            self._upkeep_info = None
        self.upkeep = []
        self.serving.close()
        await self.engine.close()
        if self.shards is not None:
            await self.shards.close()
        self.life_cycle.transition(LifeCycleState.CLOSED)

    async def _gc_janitor(self, freeze_idle_s: float,
                          refreeze_s: float = 0.0) -> None:
        """Waits for the group set to settle, then seals the heap (ONE
        deliberate collect+freeze) so the collector never walks the
        division fleet again; re-seals after later add/remove bursts, and
        — when ``raft.tpu.gc.refreeze-interval`` is set — on a steady
        cadence, moving load-accreted live objects (log entries) out of
        every future young-gen walk."""
        if freeze_idle_s <= 0 and refreeze_s <= 0:
            return
        from ratis_tpu.util import gcdiscipline
        # poll fast enough for the FASTEST configured cadence, or a
        # sub-interval refreeze would silently quantize to the default poll
        # the early return above guarantees at least one cadence is set
        cadences = [c / 2 for c in (freeze_idle_s, refreeze_s) if c > 0]
        poll = max(min(*cadences, 5.0), 0.05)
        while True:
            await asyncio.sleep(poll)
            due = (freeze_idle_s > 0
                   and gcdiscipline.seal_due(freeze_idle_s)) or \
                  (refreeze_s > 0
                   and gcdiscipline.refreeze_due(refreeze_s))
            if due:
                # inline on purpose: gc.collect holds the GIL throughout, so
                # a worker thread would stall the loop just the same — and
                # the whole point is ONE scheduled pause at a quiet moment
                took = gcdiscipline.seal()
                if took > 1.0:
                    LOG.warning("%s: heap seal paused ~%.1fs (deliberate, "
                                "post-bring-up)", self.peer_id, took)

    def seal_heap(self) -> float:
        """Imperative form of the janitor's seal, for operators/harnesses
        that know bring-up just finished and prefer to take the one
        deliberate pause NOW (the bench does).  No-op unless the server
        runs with raft.tpu.gc.discipline: sealing without the discipline's
        close-time thaw would freeze the division fleet permanently."""
        if not self._gc_disciplined:
            LOG.warning("%s: seal_heap ignored — raft.tpu.gc.discipline "
                        "is off (nothing would ever unfreeze the heap)",
                        self.peer_id)
            return 0.0
        from ratis_tpu.util import gcdiscipline
        return gcdiscipline.seal()

    # -------------------------------------------------------- group mgmt

    def _maybe_create_datastream(self, group: RaftGroup) -> None:
        if self.datastream is not None:
            return
        me = group.get_peer(self.peer_id)
        if me is not None and me.datastream_address:
            from ratis_tpu.server.datastream import DataStreamManagement
            self.datastream = DataStreamManagement(self,
                                                   me.datastream_address)

    async def _add_division(self, group: RaftGroup) -> Division:
        if group.group_id in self.divisions:
            raise AlreadyExistsException(f"{self.peer_id} already hosts {group.group_id}")
        # a group arriving after startup (group_add) may be the first to
        # advertise a datastream address for this peer
        self._maybe_create_datastream(group)
        if self.datastream is not None and not self._datastream_started \
                and self.life_cycle.get_current_state() == LifeCycleState.RUNNING:
            await self.datastream.start()
            self._datastream_started = True
        sm = self._sm_registry(group.group_id)
        storage = None
        log = None
        root = self._storage_root()
        if self._log_factory is not None:
            if root is not None:
                # A durable server with a volatile injected log would persist
                # term/vote while losing acked entries on restart — a
                # committed-data-loss hazard.  Refuse the combination.
                raise ValueError(
                    "log_factory cannot be combined with durable storage; "
                    "set raft.server.log.use.memory=true")
            log = self._log_factory(self, group)
        elif root is not None:
            from ratis_tpu.server.log.segmented import LogWorker, SegmentedRaftLog
            from ratis_tpu.server.storage import RaftStorageDirectory
            storage = RaftStorageDirectory(root, group.group_id)
            storage.format()
            storage.lock()
            if RaftServerConfigKeys.TpuLog.shared(self.properties):
                from ratis_tpu.server.log.shared import SharedGroupLog
                store = self._shared_log_store(root,
                                               self.shard_of_group(
                                                   group.group_id))
                log = SharedGroupLog(
                    f"log-{self.peer_id}-{group.group_id}",
                    group.group_id.to_bytes(), store)
            else:
                log = SegmentedRaftLog(
                    f"log-{self.peer_id}-{group.group_id}", storage.current,
                    worker=LogWorker.shared(f"{self.peer_id}:{root}"),
                    segment_size_max=RaftServerConfigKeys.Log
                    .segment_size_max(self.properties),
                    cache_segments_max=RaftServerConfigKeys.Log
                    .segment_cache_num_max(self.properties))
        div = Division(self, group, sm, log=log, storage=storage)
        self.divisions[group.group_id] = div
        if self._gc_disciplined:
            from ratis_tpu.util import gcdiscipline
            gcdiscipline.note_mutation()
        try:
            # sharded: the division LIVES on its pinned loop from the first
            # task it spawns (apply loop, election machinery, windows)
            await self._run_on_division_loop(group.group_id, div.start())
        except Exception:
            self.divisions.pop(group.group_id, None)
            try:
                await self._run_on_division_loop(group.group_id, div.close())
            except Exception:
                LOG.exception("%s: cleanup after failed start of %s",
                              self.peer_id, group.group_id)
            raise
        return div

    async def group_add(self, group: RaftGroup) -> Division:
        return await self._add_division(group)

    async def group_remove(self, group_id: RaftGroupId,
                           delete_directory: bool = False) -> None:
        div = self.divisions.pop(group_id, None)
        if div is None:
            raise GroupMismatchException(f"{self.peer_id} does not host {group_id}")
        if self._gc_disciplined:
            from ratis_tpu.util import gcdiscipline
            gcdiscipline.note_mutation()
        await div.state_machine.notify_group_remove()
        storage = div.storage
        await self._run_on_division_loop(group_id, div.close())
        if delete_directory and storage is not None:
            import shutil
            await asyncio.to_thread(
                shutil.rmtree, storage.root, ignore_errors=True)

    async def bootstrap_division(self, group_id: RaftGroupId) -> None:
        """Appointed-leader bootstrap on the division's own loop (harness/
        operator entry point; Division.bootstrap_as_leader is loop-affine
        like every other division method)."""
        div = self.get_division(group_id)
        await self._run_on_division_loop(group_id, div.bootstrap_as_leader())

    def get_division(self, group_id: RaftGroupId) -> Division:
        div = self.divisions.get(group_id)
        if div is None:
            raise GroupMismatchException(
                f"{self.peer_id} does not serve {group_id}; groups: "
                f"{[str(g) for g in self.divisions]}")
        return div

    def group_ids(self) -> list[RaftGroupId]:
        return list(self.divisions)

    # ------------------------------------------------------------- routing

    def _shared_log_store(self, root: str, shard: int):
        """Get-or-create the shard's interleaved log store.  Each shard
        gets its OWN LogWorker: worker futures are created on the
        submitter's loop, and a shard's divisions all live on one loop, so
        per-shard workers keep every future loop-affine (the per-group
        store's single per-device worker would cross loops here)."""
        store = self._shared_log_stores.get(shard)
        if store is None:
            from ratis_tpu.server.log.segmented import LogWorker
            from ratis_tpu.server.log.shared import (SharedLogStore,
                                                     shard_dir)
            store = SharedLogStore(
                shard_dir(root, shard),
                LogWorker.shared(f"{self.peer_id}:{root}:shard{shard}"),
                segment_size_max=RaftServerConfigKeys.TpuLog
                .shared_segment_size_max(self.properties),
                compaction_dead_ratio=RaftServerConfigKeys.TpuLog
                .compaction_dead_ratio(self.properties),
                name=f"sharedlog-{self.peer_id}-shard{shard}",
                on_final_release=lambda s=shard:
                self._shared_log_stores.pop(s, None))
            self._shared_log_stores[shard] = store
        return store

    def shard_of_group(self, group_id: RaftGroupId) -> int:
        """Loop-shard index owning ``group_id``'s division (0 unsharded)."""
        if self.shards is None:
            return 0
        return self.shards.shard_of(group_id.to_bytes())

    def slice_of_group(self, group_id: RaftGroupId) -> int:
        """Mesh slice owning ``group_id``'s engine rows (0 without a
        mesh).  Same crc32 hash as :meth:`shard_of_group`, so whenever
        ``mesh-devices`` divides ``loop-shards`` one device slice is fed
        by a stable subset of loop shards (one slice = one shard-set)."""
        return self.engine.slice_of(group_id.to_bytes())

    def upkeep_plane_for(self, shard: int):
        """The loop shard's UpkeepPlane, or None when array mode is off
        (raft.tpu.upkeep.enabled unset) — callers fall back to the legacy
        per-group paths."""
        if not self.upkeep:
            return None
        return self.upkeep[shard]

    def shard_queue_depth(self, group_id: RaftGroupId) -> int:
        """Ready-callback backlog of the loop owning ``group_id``'s
        division (-1 unknown) — the queueing signal the /divisions
        endpoint surfaces per division."""
        from ratis_tpu.server.shards import loop_ready_depth
        if self.shards is not None:
            return self.shards.queue_depth(self.shard_of_group(group_id))
        try:
            return loop_ready_depth(asyncio.get_running_loop())
        except RuntimeError:
            return -1

    # -------------------------------------------- observability endpoints

    def health_info(self) -> dict:
        """GET /health: liveness + engine tick freshness.  The engine tick
        is the server's heartbeat-of-heartbeats — a stale tick means every
        hosted group's election/commit math is stalled."""
        import os
        import time as _time
        last = self.engine.last_tick_monotonic
        age = (None if last is None
               else round(_time.monotonic() - last, 3))
        # fresh = the tick loop ran within a generous multiple of its
        # cadence (the loop sleeps at most tick_interval between passes;
        # 50x tolerates load, a floor of 2s tolerates tiny intervals)
        fresh_bound = max(2.0, 50 * self.engine.tick_interval_s)
        state = self.life_cycle.get_current_state().name
        ok = (state == "RUNNING" and age is not None and age < fresh_bound)
        return {
            "status": "ok" if ok else "degraded",
            "peer": str(self.peer_id),
            "address": self.address,
            "pid": os.getpid(),
            "lifecycle": state,
            "divisions": len(self.divisions),
            "loopShards": self.loop_shards,
            "engine": {
                "ticks": self.engine.metrics["ticks"],
                "lastTickAgeS": age,
                "freshBoundS": fresh_bound,
                "groupsLive": len(self.engine.state.active),
                "groupsCapacity": self.engine.state.capacity,
                "meshSlices": self.engine.state.n_slices,
            },
            "watchdogEvents": (self.watchdog.event_count()
                               if self.watchdog is not None else 0),
            "serving": {
                "admissionEnabled": self.serving.admission.enabled,
                "shedTotal": self.serving.admission.shed_total,
                "pendingCount": sum(self.serving.admission.pending_count),
                "pendingBytes": sum(self.serving.admission.pending_bytes),
            },
            "chaos": self.chaos_info(),
        }

    def chaos_info(self) -> dict:
        """Active injected faults (the /health ``chaos`` block): link
        faults touching this peer from the process-wide chaos table, plus
        any registered code-injection points.  All-empty on a production
        server (the table is only consulted with raft.tpu.chaos.enabled,
        and nothing registers injections outside a campaign)."""
        from ratis_tpu.chaos.link import link_faults
        from ratis_tpu.util import injection as _inj
        me = str(self.peer_id)
        links = [f for f in link_faults().active()
                 if f["src"] in (me, None) or f["dst"] in (me, None)]
        points = [p for p in (_inj.APPEND_TRANSACTION, _inj.LOG_SYNC,
                              _inj.RUN_LOG_WORKER, _inj.REQUEST_VOTE,
                              _inj.APPEND_ENTRIES, _inj.INSTALL_SNAPSHOT)
                  if _inj.is_registered(p)]
        return {"activeLinkFaults": links, "activeInjections": points}

    def divisions_info(self, query=None):
        """GET /divisions: per-division introspection (role, term,
        commit/applied, follower lag, cache sizes, shard placement).
        ``?rollup=1`` returns the cheap per-server rollup instead —
        leadership count, total pending, shard occupancy vector — the
        O(servers) payload the placement frontends aggregate without
        shipping (or parsing) every division's full introspection."""
        if query and query.get("rollup", [None])[0]:
            n_shards = self.shards.n if self.shards is not None else 1
            shard_counts = [0] * n_shards
            leading = pending = hibernating = 0
            for div in list(self.divisions.values()):
                shard_counts[self.shard_of_group(div.group_id)
                             % n_shards] += 1
                if div.hibernating:
                    hibernating += 1
                if div.is_leader() and div.leader_ctx is not None:
                    leading += 1
                    pending += len(div.leader_ctx.pending)
            import os
            return {"peer": str(self.peer_id), "pid": os.getpid(),
                    "divisions": len(self.divisions),
                    "leading": leading, "pendingTotal": pending,
                    "hibernating": hibernating, "shards": shard_counts}
        return [div.introspect()
                for div in list(self.divisions.values())]

    def lag_info(self, query=None) -> dict:
        """GET /lag: the lag & health ledger — per-peer link/health
        rollups with log2 lag histograms, plus the top-k laggard groups
        (``?n=<k>`` overrides raft.tpu.lag.top-groups).  One fused engine
        pass + one device fetch, O(peers + k) python."""
        import os

        import numpy as np
        n = self.lag_top_groups
        if query:
            try:
                n = int(query.get("n", [None])[0])
            except (TypeError, ValueError):
                pass
        ledger = self.engine.ledger
        s = ledger.sample()
        peers = []
        for i, name in enumerate(s.peer_names):
            links = int(s.peer_links[i])
            if links == 0:
                continue
            up = int(s.peer_up[i])
            active = int(s.peer_active[i])
            laggy_active = int(s.peer_laggy_active[i])
            # health score: healthy share of the links that matter —
            # 1.0 = every active link inside the lag threshold; down
            # links count against the score like laggy ones
            down = links - up
            bad = laggy_active + down
            score = round(1.0 - bad / max(1, active + down), 4)
            hist = {int(b): int(c)
                    for b, c in enumerate(s.hist[i]) if c}
            peers.append({
                "peer": name, "links": links, "up": up, "down": down,
                "laggy": int(s.peer_laggy[i]), "active": active,
                "laggyActive": laggy_active,
                "maxLag": max(0, int(s.peer_max_lag[i])),
                "score": score, "hist": hist,
            })
        groups = []
        order = np.argsort(-s.worst_lag, kind="stable")
        for slot in order[:max(0, n)]:
            lag = int(s.worst_lag[slot])
            if lag <= 0:
                break  # sorted: nothing laggy past here
            listener = self.engine._listeners.get(int(slot))
            if listener is None:
                continue
            gid = listener.group_id
            peer_idx = int(s.worst_peer[slot])
            groups.append({
                "group": str(gid), "lag": lag,
                "peer": (s.peer_names[peer_idx]
                         if 0 <= peer_idx < len(s.peer_names) else "?"),
                "commit": int(s.commit[slot]),
                "gap": int(s.gap[slot]),
                "shard": self.shard_of_group(gid),
            })
        return {
            "peer": str(self.peer_id),
            "pid": os.getpid(),
            "now_ms": s.now_ms,
            "lagThreshold": ledger.lag_threshold,
            "upWindowMs": ledger.up_window_ms,
            "leading": s.leading,
            "gapTotal": s.gap_total,
            "fetchMs": s.fetch_ms,
            "peers": peers,
            "groups": groups,
        }

    def watchdog_events(self, query=None) -> dict:
        """GET /events: the stall watchdog's bounded event journal.
        ``?since=<seq>`` serves only records newer than that monotonic
        seq id — the flight recorder and ``shell top`` poll
        incrementally instead of re-deduping the whole ring."""
        if self.watchdog is None:
            return {"enabled": False, "seq": -1, "events": []}
        since = None
        if query:
            try:
                since = int(query.get("since", [None])[0])
            except (TypeError, ValueError):
                since = None
        return {"enabled": True,
                "count": self.watchdog.event_count(),
                "seq": self.watchdog.last_seq,
                "events": self.watchdog.events(since)}

    async def _run_on_division_loop(self, group_id: RaftGroupId, coro):
        """Await ``coro`` on the loop owning ``group_id``'s division; a
        plain await when unsharded or already on the owning loop."""
        if self.shards is None:
            return await coro
        return await self.shards.run_on(self.shard_of_group(group_id), coro)

    async def _handle_server_rpc(self, msg):
        from ratis_tpu.protocol.raftrpc import BulkHeartbeat
        if isinstance(msg, AppendEnvelope):
            return await self._handle_append_envelope(msg)
        if isinstance(msg, BulkHeartbeat):
            return await self._handle_bulk_heartbeat(msg)
        if self.shards is not None:
            # division state is loop-affine: handle on the owning shard
            # (exceptions — e.g. GroupMismatch — propagate back through the
            # wrapped future unchanged)
            return await self.shards.run_on(
                self.shard_of_group(msg.header.group_id),
                self._handle_division_rpc(msg))
        return await self._handle_division_rpc(msg)

    async def _handle_division_rpc(self, msg):
        div = self.get_division(msg.header.group_id)
        if isinstance(msg, AppendEntriesRequest):
            return await div.handle_append_entries(msg)
        if isinstance(msg, RequestVoteRequest):
            return await div.handle_request_vote(msg)
        if isinstance(msg, InstallSnapshotRequest):
            return await div.handle_install_snapshot(msg)
        if isinstance(msg, ReadIndexRequest):
            return await div.handle_read_index(msg)
        if isinstance(msg, StartLeaderElectionRequest):
            return await div.handle_start_leader_election(msg)
        raise RaftException(f"unknown server rpc {type(msg).__name__}")

    # bounded lane table: dead lanes (sender restarts / lane re-cuts) are
    # LRU-evicted; live lanes (parked or processing frames) are never
    # evicted mid-flight
    _LANE_TABLE_MAX = 512
    # hard per-lane cap on IN-ORDER frames queued behind a busy
    # predecessor (memory bound; matches the sender-side lane-slot
    # ceiling, so a healthy sender never hits it)
    _LANE_QUEUE_MAX = 64

    async def _handle_append_envelope(self, env: AppendEnvelope
                                      ) -> AppendEnvelopeReply:
        """Follower intake of a multi-group append frame.  Unsequenced
        frames (seq < 0 — depth-1 senders, the legacy protocol) apply
        immediately; sequenced lane frames are sequence-checked first and
        process strictly in lane order (out-of-order arrivals briefly
        buffered, gaps rejected with a rewind hint) — the receiver half of
        the append-window pipeline."""
        if env.seq < 0:
            return await self._apply_append_envelope(env)
        return await self._handle_sequenced_envelope(env)

    async def _handle_sequenced_envelope(self, env: AppendEnvelope
                                         ) -> AppendEnvelopeReply:
        from ratis_tpu.protocol.raftrpc import ENV_OUT_OF_SEQUENCE
        loop = asyncio.get_running_loop()
        # lane ids are unique per sender lifetime; the requestor id keys
        # co-hosted processes apart even across pid reuse
        requestor = (env.items[0].header.requestor_id if env.items
                     else None)
        key = (requestor, env.lane)
        st = self._lanes.get(key)
        if st is None:
            st = _LaneIntake(env.seq)
            self._lanes[key] = st
            if len(self._lanes) > self._LANE_TABLE_MAX:
                idle = [(s.last_used, k) for k, s in self._lanes.items()
                        if k != key and not s.busy and not s.waiting]
                if idle:
                    victim = self._lanes.pop(min(idle)[1], None)
                    if victim is not None:
                        victim.close()
        st.last_used = loop.time()

        def reject() -> AppendEnvelopeReply:
            self.lane_metrics["lane_rejects"] += 1
            return AppendEnvelopeReply((), status=ENV_OUT_OF_SEQUENCE,
                                       hint=st.next_process)

        if env.seq < st.next_process or env.seq in st.waiting \
                or (st.busy and env.seq == st.next_process):
            return reject()  # duplicate / stale frame: never re-process
        if env.seq > st.next_arrival:
            self.lane_metrics["ooo_buffered"] += 1  # genuine reorder
        st.next_arrival = max(st.next_arrival, env.seq + 1)
        if st.busy or env.seq != st.next_process:
            # park until our turn.  IN-ORDER frames queued behind a busy
            # predecessor are ordinary pipelining (bounded only by the
            # hard lane-queue cap — the sender's slot window keeps this
            # small); frames parked past a sequence HOLE (arrived
            # unprocessed frames don't account for every seq below us)
            # are genuine reorders, bounded by the reorder buffer, and a
            # hole that never fills trips the lane's gap timer and
            # rejects every parked frame
            arrived = len(st.waiting) + (1 if st.busy else 0)
            hole = arrived < env.seq - st.next_process
            limit = (self.reorder_buffer if hole
                     else self._LANE_QUEUE_MAX)
            if len(st.waiting) >= limit:
                return reject()
            fut = loop.create_future()
            st.waiting[env.seq] = fut
            st.arm_gap_timer(loop)
            try:
                # a normal wake IS the ownership hand-off (busy stays
                # True across the transfer — see _LaneIntake.pass_on)
                await fut
            except _LaneGap:
                return reject()
            except asyncio.CancelledError:
                if st.waiting.get(env.seq) is fut:
                    st.waiting.pop(env.seq, None)
                elif fut.done() and not fut.cancelled():
                    # ownership had just been handed to us: pass it on so
                    # the lane is not wedged by our cancellation
                    st.pass_on(loop)
                raise
        else:
            st.busy = True
        self.lane_metrics["lane_frames"] += 1
        try:
            # ADMISSION is the synchronous part: the frame's group runs
            # are created (and their per-group order chains registered)
            # before the lane admits the next frame — so cross-frame
            # per-group FIFO is fixed here, and frames then PROCESS
            # concurrently (distinct groups never wait on each other's
            # frames; the legacy envelope concurrency, kept)
            pending = self._start_append_envelope(env)
        finally:
            st.next_process = env.seq + 1
            st.last_used = loop.time()
            st.pass_on(loop)
        return await pending

    async def _apply_append_envelope(self, env: AppendEnvelope
                                     ) -> AppendEnvelopeReply:
        return await self._start_append_envelope(env)

    def _start_append_envelope(self, env: AppendEnvelope):
        """Sweep intake: fan the frame out to its divisions; returns the
        awaitable producing the frame's batched ack reply.  Groups are
        independent, so distinct groups are handled concurrently; one
        group's items are handled sequentially in envelope order, and —
        for sequenced frames, whose groups MAY span consecutive frames —
        a per-group completion chain orders frame k+1's run for a group
        after frame k's (registered synchronously in admission order, on
        the group's owning loop).  A group this server doesn't host
        yields None — a per-group error, not an envelope failure.  In
        sweep mode every item's engine flush update is collected and
        enters the engine as ONE batched intake after the whole frame has
        appended (one intake-lock round-trip per frame instead of one per
        item)."""
        items = env.items
        chained = env.seq >= 0
        results: list = [None] * len(items)
        # per-item flush rows (index-disjoint, so cross-shard writes are
        # safe); batched into one engine intake below
        flush_rows: Optional[list] = ([None] * len(items)
                                      if self.replication_sweep else None)
        by_group: dict = {}
        for i, req in enumerate(items):
            by_group.setdefault(req.header.group_id, []).append(i)

        def register_chain(gid):
            """Per-group cross-frame order link; called synchronously on
            the group's owning loop, in frame admission order."""
            if not chained:
                return None, None
            prev = self._group_chains.get(gid)
            fut = asyncio.get_running_loop().create_future()
            self._group_chains[gid] = fut
            return prev, fut

        async def run_group(gid, idxs, prev, fut):
            try:
                if prev is not None:
                    try:
                        await prev  # frame k's run for this group
                    except Exception:
                        pass
                for i in idxs:
                    try:
                        div = self.get_division(
                            items[i].header.group_id)
                        if flush_rows is None:
                            results[i] = await div.handle_append_entries(
                                items[i])
                        else:
                            rows: list = []
                            flush_rows[i] = rows
                            results[i] = await div.handle_append_entries(
                                items[i], flush_sink=rows)
                    except Exception:
                        results[i] = None
            finally:
                if fut is not None:
                    if not fut.done():
                        fut.set_result(None)
                    if self._group_chains.get(gid) is fut:
                        del self._group_chains[gid]

        if self.shards is None:
            # chains registered NOW (synchronously, in admission order);
            # gather creates the group tasks in the same breath
            aw = asyncio.gather(
                *(run_group(gid, ix, *register_chain(gid))
                  for gid, ix in by_group.items()))
        else:
            # sharded: each group's ordered run executes on its owning
            # loop; groups on one shard still run concurrently there
            # (gather inside the shard hop), shards run in parallel.  The
            # flat results list is index-disjoint across groups, so
            # cross-thread writes are safe.  Chain registration happens
            # as the shard coroutine's FIRST synchronous step: shard
            # submissions preserve admission order per loop
            # (run_coroutine_threadsafe is FIFO), so registration order
            # equals admission order there too.
            by_shard: dict[int, list] = {}
            for gid, idxs in by_group.items():
                by_shard.setdefault(self.shard_of_group(gid),
                                    []).append((gid, idxs))

            async def run_shard(group_runs):
                await asyncio.gather(
                    *(run_group(gid, ix, *register_chain(gid))
                      for gid, ix in group_runs))

            aw = asyncio.gather(*(self.shards.run_on(k, run_shard(v))
                                  for k, v in by_shard.items()))

        async def finish() -> AppendEnvelopeReply:
            await aw
            if flush_rows is not None:
                rows = [r for sub in flush_rows if sub for r in sub]
                if rows:
                    self.engine.on_flush_batch(rows)
            return AppendEnvelopeReply(tuple(results))

        return finish()

    async def _handle_bulk_heartbeat(self, msg):
        """Follower side of the compact multi-group heartbeat: one small
        per-division happy-path step per item (leadership recognition +
        deadline reset + log-matching-gated commit advance).  Items whose
        division append lock is free run inline (the happy path never
        suspends, so the sweep stays a tight loop); items contending with an
        in-flight append are skipped with BULK_HB_BUSY so ONE division's
        slow flush never head-of-line-blocks heartbeat delivery for later
        divisions, nor the envelope's reply (and with it every co-hosted
        group's ack freshness at the leader).  The skipped division's
        election deadline is safe: the very append holding its lock resets
        it on completion, and the leader retries next sweep.  Groups this
        server doesn't host reply UNKNOWN_GROUP."""
        from ratis_tpu.protocol.ids import RaftGroupId
        from ratis_tpu.protocol.raftrpc import (BULK_HB_BUSY,
                                                BULK_HB_UNKNOWN_GROUP,
                                                BulkHeartbeatReply)
        src = msg.requestor_id
        items = msg.items
        miss = (BULK_HB_UNKNOWN_GROUP, -1, -1, -1, -1)
        busy = (BULK_HB_BUSY, -1, -1, -1, -1)
        results: list = [miss] * len(items)

        async def run_items(idxs) -> None:
            done = 0
            for n in idxs:
                item = items[n]
                gid_bytes, term, commit, commit_term = item[:4]
                hibernate = len(item) > 4 and bool(item[4])
                div = self.divisions.get(RaftGroupId.value_of(gid_bytes))
                if div is None:
                    pass  # results[n] stays UNKNOWN_GROUP
                elif div.append_lock_locked():
                    results[n] = busy
                else:
                    try:
                        results[n] = await div.on_bulk_heartbeat(
                            src, term, commit, commit_term,
                            hibernate=hibernate)
                    except Exception:
                        LOG.exception("%s bulk heartbeat item failed",
                                      self.peer_id)
                done += 1
                if done % 1024 == 0:
                    # coarse yield cadence, same rationale as the sweep's:
                    # on a loaded loop each yield waits out the ready
                    # backlog, and heartbeat DELIVERY latency is an
                    # election-liveness input
                    await asyncio.sleep(0)

        if self.shards is None:
            await run_items(range(len(items)))
        else:
            # item handling is loop-affine (division append locks/deadline
            # state): split the bulk by owning shard, handle shard slices
            # in parallel, keep per-item reply alignment via the shared
            # index-disjoint results list
            by_shard: dict[int, list[int]] = {}
            for n, item in enumerate(items):
                gid = RaftGroupId.value_of(item[0])
                by_shard.setdefault(self.shard_of_group(gid), []).append(n)
            await asyncio.gather(*(self.shards.run_on(k, run_items(v))
                                   for k, v in by_shard.items()))
        return BulkHeartbeatReply(tuple(results))

    async def _handle_client_request(self, request: RaftClientRequest
                                     ) -> RaftClientReply:
        from ratis_tpu.protocol.requests import RequestType
        from ratis_tpu.trace.tracer import INGRESS_NS, STAGE_ROUTE, TRACER
        trace_t0 = 0
        if TRACER.enabled and request.trace_id:
            # route starts at transport ingress when the transport stamped
            # it (captures the ingress->handler scheduling hop), else here
            trace_t0 = INGRESS_NS.get() or TRACER.now()
            INGRESS_NS.set(0)  # single-use: never bleed into a later call
        t = request.type.type
        if t == RequestType.GROUP_MANAGEMENT:
            return await self._group_management(request)
        if t == RequestType.GROUP_LIST:
            from ratis_tpu.protocol.admin import encode_group_list
            from ratis_tpu.protocol.message import Message
            return RaftClientReply.success_reply(
                request, message=Message(encode_group_list(self.group_ids())))
        try:
            div = self.get_division(request.group_id)
        except GroupMismatchException as e:
            return RaftClientReply.failure_reply(request, e)
        # Admission control (serving plane): a shard over its pending
        # budget sheds here with a typed overload reply — the request
        # never hops to the saturated division loop.
        shed, ticket = self.serving.admission.try_admit(request)
        if shed is not None:
            return shed
        wrapped_sink = False
        if ticket is not None:
            from ratis_tpu.protocol.requests import (attach_reply_sink,
                                                     reply_sink_of)
            sink = reply_sink_of(request)
            if sink is not None:
                # deferred replies bypass the handler return: the budget
                # is held until the waterline fan-out delivers through
                # the transport sink
                def _release_sink(reply, _sink=sink, _t=ticket):
                    _t.release()
                    _sink(reply)
                attach_reply_sink(request, _release_sink)
                wrapped_sink = True
        if trace_t0:
            TRACER.record(request.trace_id, STAGE_ROUTE, trace_t0,
                          TRACER.now())
        deferred = False
        try:
            try:
                # sharded: the division's whole submit path (windows, append,
                # quorum wait, apply wait) runs on its pinned loop
                reply = await self._run_on_division_loop(
                    request.group_id, div.submit_client_request(request))
            except RaftException as e:
                return RaftClientReply.failure_reply(request, e)
            except Exception as e:  # never leak raw errors to the wire
                LOG.exception("%s request failed", self.peer_id)
                return RaftClientReply.failure_reply(
                    request, RaftException(str(e)))
            if reply is DEFERRED_REPLY:
                # deferred-reply fast path: the waterline fan-out delivers the
                # real reply through the request's transport sink at commit
                # (the respond span is recorded there, not via mark_egress)
                deferred = True
                return reply
            if trace_t0:
                # the transport pops this to close the respond span (handler
                # done -> reply serialized/handed back)
                TRACER.mark_egress(request.trace_id)
            return reply
        finally:
            if ticket is not None and not (deferred and wrapped_sink):
                ticket.release()

    async def submit_data_stream_request(self, request: RaftClientRequest
                                         ) -> RaftClientReply:
        """Primary-side raft submit of a completed DataStream
        (DataStreamManagement.java:139-193: on CLOSE the primary drives the
        header request through the ordinary consensus path).  The primary
        may not be the leader — forward like any client request would be."""
        try:
            div = self.get_division(request.group_id)
            reply = await self._run_on_division_loop(
                request.group_id, div.submit_client_request(request))
        except RaftException as e:
            return RaftClientReply.failure_reply(request, e)
        nle = reply.get_not_leader_exception()
        if nle is not None and nle.suggested_leader is not None:
            peer = nle.suggested_leader
            address = peer.get_client_address() or \
                self.resolve_peer_address(peer.id)
            if address:
                try:
                    forward = self._transport_factory.new_client_transport(
                        self.properties)
                    try:
                        return await forward.send_request(address, request)
                    finally:
                        await forward.close()
                except Exception as e:
                    return RaftClientReply.failure_reply(
                        request, RaftException(f"forward to leader: {e}"))
        return reply

    async def _group_management(self, request: RaftClientRequest
                                ) -> RaftClientReply:
        """GroupManagementApi server side (RaftServerProxy
        groupManagementAsync:490 / groupAddAsync:509 / groupRemoveAsync:540)."""
        from ratis_tpu.protocol.admin import (GroupManagementArguments,
                                              GroupManagementOp)
        try:
            args = GroupManagementArguments.from_payload(request.message.content)
        except Exception as e:
            return RaftClientReply.failure_reply(
                request, RaftException(f"bad groupManagement payload: {e}"))
        try:
            if args.op == GroupManagementOp.ADD:
                if args.group is None:
                    raise RaftException("group add without a group")
                await self.group_add(args.group)
            elif args.op == GroupManagementOp.REMOVE:
                if args.group_id is None:
                    raise RaftException("group remove without a group id")
                await self.group_remove(args.group_id, args.delete_directory)
            else:
                raise RaftException(f"unknown group op {args.op}")
        except RaftException as e:
            return RaftClientReply.failure_reply(request, e)
        except Exception as e:
            LOG.exception("%s group management failed", self.peer_id)
            return RaftClientReply.failure_reply(request, RaftException(str(e)))
        return RaftClientReply.success_reply(request)

    def reply_hops_per_commit(self) -> float:
        """Reply-plane scheduling hops per commit advance — the fan-out
        collapse's standing metric.  Hops are PROCESS-wide (co-hosted
        servers share the counters, like the tracer); the commit
        denominator is this server's engine, so in a one-server-per-
        process deployment the ratio is exact and in an in-process test
        cluster it is a per-server upper bound (the bench divides by the
        cluster-wide commit sum instead)."""
        from ratis_tpu.metrics import hops as hops_mod
        commits = max(1, self.engine.metrics["commit_advances"])
        return round(hops_mod.reply_plane_hops() / commits, 4)

    def resolve_peer_address(self, peer_id: RaftPeerId) -> Optional[str]:
        return self.peer_addresses.get(peer_id)

    def learn_peer_addresses(self, peers) -> None:
        for p in peers:
            if p.address:
                self.peer_addresses[p.id] = p.address

    async def send_server_rpc(self, to: RaftPeerId, msg):
        return await self.transport.send_server_rpc(to, msg)

    def __str__(self) -> str:
        return f"RaftServer({self.peer_id}@{self.address}, {len(self.divisions)} groups)"
