"""Intake admission control: per-loop-shard bounded pending budgets.

Reference analog: RaftServerImpl's resource checks over PendingRequests'
element/byte limits (PendingRequests.java RequestLimits) — a request past
the limit is rejected with ResourceUnavailableException instead of being
queued.  Here the budget is per loop shard (the unit that saturates: one
shard's event loop backs up while its neighbors idle), counted at the
single client intake all transports share, and the typed reply carries a
retry-after hint the client's retry loop honors.

A shed request never reaches the division loop — the reply is synthesized
at intake, so a saturated shard's rejection path costs one dict hop and
no cross-loop scheduling.
"""

from __future__ import annotations

import logging
from typing import Optional

from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.protocol.exceptions import ResourceUnavailableException
from ratis_tpu.protocol.requests import (RaftClientReply, RaftClientRequest,
                                         RequestType)

LOG = logging.getLogger(__name__)

# Request types that consume pending budget: the data plane.  Admin
# traffic (group management, snapshot ops, conf changes) is rare, small,
# and must stay serviceable while the data plane sheds.
_BUDGETED = frozenset({
    RequestType.WRITE, RequestType.READ, RequestType.STALE_READ,
    RequestType.WATCH, RequestType.MESSAGE_STREAM, RequestType.DATA_STREAM,
    RequestType.FORWARD,
})


class _Ticket:
    """One admitted request's budget hold; release is idempotent (the
    intake's finally and the deferred-reply sink wrapper can both fire)."""

    __slots__ = ("ctrl", "shard", "nbytes", "released")

    def __init__(self, ctrl: "AdmissionController", shard: int, nbytes: int):
        self.ctrl = ctrl
        self.shard = shard
        self.nbytes = nbytes
        self.released = False

    def release(self) -> None:
        if self.released:
            return
        self.released = True
        self.ctrl.pending_count[self.shard] -= 1
        self.ctrl.pending_bytes[self.shard] -= self.nbytes


class AdmissionController:
    """Per-shard pending count/byte budgets with typed overload replies.

    With admission disabled (the default) ``try_admit`` returns
    ``(None, None)`` without touching any counter — the request path is
    exactly the pre-serving-plane path."""

    def __init__(self, server) -> None:
        p = server.properties
        keys = RaftServerConfigKeys.Serving
        self.server = server
        self.enabled = keys.admission_enabled(p)
        self.element_limit = keys.pending_element_limit(p)
        self.byte_limit = keys.pending_byte_limit(p)
        self.retry_after_ms = max(1, int(keys.retry_after(p).seconds * 1000))
        self.n_shards = max(1, server.loop_shards or 1)
        self.pending_count = [0] * self.n_shards
        self.pending_bytes = [0] * self.n_shards
        self.admitted_total = 0
        self.shed_total = 0
        self.shed_by_shard = [0] * self.n_shards

    def try_admit(self, request: RaftClientRequest
                  ) -> tuple[Optional[RaftClientReply], Optional[_Ticket]]:
        """(shed_reply, None) when over budget; (None, ticket) when the
        request was admitted and holds budget until ``ticket.release()``;
        (None, None) when admission does not apply (disabled or an
        exempt admin request type)."""
        if not self.enabled or request.type.type not in _BUDGETED:
            return None, None
        div = self.server.divisions.get(request.group_id)
        if div is not None and not div.is_leader():
            # a group this server does not lead holds no pending
            # capacity: the division replies NotLeader (or serves a
            # stale read locally) without entering the commit pipeline.
            # Shedding here would hide the redirect hint — after a
            # leadership transfer the old leader would trap its clients
            # in retry-after loops instead of healing their routing
            return None, None
        shard = self.server.shard_of_group(request.group_id)
        nbytes = len(request.message.content) if request.message else 0
        count = self.pending_count[shard]
        size = self.pending_bytes[shard]
        if count >= self.element_limit or size + nbytes > self.byte_limit:
            self.shed_total += 1
            self.shed_by_shard[shard] += 1
            # scale the hint with overshoot so a deeply saturated shard
            # pushes clients further out than one grazing the limit
            over = max(count / max(1, self.element_limit),
                       (size + nbytes) / max(1, self.byte_limit))
            hint_ms = int(self.retry_after_ms * min(8.0, max(1.0, over)))
            return RaftClientReply.failure_reply(request, ResourceUnavailableException(
                f"{self.server.peer_id} shard {shard} over pending budget "
                f"({count}/{self.element_limit} requests, "
                f"{size}/{self.byte_limit} bytes)",
                retry_after_ms=hint_ms)), None
        self.pending_count[shard] = count + 1
        self.pending_bytes[shard] = size + nbytes
        self.admitted_total += 1
        return None, _Ticket(self, shard, nbytes)
