"""Batched readIndex confirmation: one sweep per shard, all groups at once.

The scalar path (Division._confirm_leadership) proves leadership with one
empty-append round per group per read burst — at 1024 groups with
concurrent readers that is 1024 heartbeat round trips per sweep interval,
exactly the O(groups) RPC wall the replication envelope removed for
appends.  This scheduler coalesces every group with a pending
linearizable read on a loop shard into ONE zero-entry unsequenced
AppendEnvelope per destination peer (seq=-1: processed immediately,
bit-identical to the legacy frame), and counts each group's majority from
the envelope reply's aligned per-item AppendEntriesReplies.

The confirmation semantics per group are exactly the scalar path's: an
empty AppendEntriesRequest at the group's current term, acked by SUCCESS
or INCONSISTENCY (either proves the follower recognizes this term's
leader — ReadIndexHeartbeats' AppendEntriesListeners:126), majority
counted excluding self.  Only the transport framing is batched.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.protocol.exceptions import ReadIndexException
from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest, AppendEnvelope,
                                        AppendResult, RaftRpcHeader)

LOG = logging.getLogger(__name__)


class _Entry:
    """One group's pending confirmation in the next sweep."""

    __slots__ = ("division", "future", "waiters")

    def __init__(self, division, future: asyncio.Future):
        self.division = division
        self.future = future
        self.waiters = 1


class _ShardState:
    __slots__ = ("pending", "armed")

    def __init__(self):
        self.pending: dict = {}  # group_id -> _Entry
        self.armed = False


class ReadIndexScheduler:
    """Per-shard cross-group readIndex confirmation sweeps.

    ``confirm(division)`` is called on the division's loop; all of a
    shard's state is touched only from that shard's loop, so no locks.
    Reads arriving in the same event-loop pass (plus an optional
    ``read-batch.window`` delay) share one sweep; concurrent reads of one
    group share one future within a sweep."""

    def __init__(self, server) -> None:
        p = server.properties
        self.server = server
        self.window_s = \
            RaftServerConfigKeys.Serving.read_batch_window(p).seconds
        self.timeout_s = RaftServerConfigKeys.Read.timeout(p).seconds
        self._shards: dict[int, _ShardState] = {}
        self.sweeps = 0       # batched confirmation rounds fired
        self.confirmed = 0    # reads whose confirmation rode a sweep
        # destination peer name -> confirmation group-requests sent (the
        # placement bench's grey-confirmation-share denominator)
        self.confirm_sent: dict[str, int] = {}

    def confirm(self, division) -> asyncio.Future:
        """Future resolving when ``division``'s leadership is confirmed by
        a batched sweep (ReadIndexException on failure).  Callers should
        ``asyncio.shield`` the await: the future is shared by every
        concurrent reader of the group in this sweep."""
        loop = asyncio.get_running_loop()
        others = [p for p in division.state.configuration.voting_peers()
                  if p.id != division.member_id.peer_id]
        if not others:
            # single-voter group: leadership is self-evident, no round
            fut = loop.create_future()
            fut.set_result(None)
            return fut
        shard = self.server.shard_of_group(division.group_id)
        state = self._shards.setdefault(shard, _ShardState())
        entry = state.pending.get(division.group_id)
        if entry is not None:
            entry.waiters += 1
            return entry.future
        entry = _Entry(division, loop.create_future())
        state.pending[division.group_id] = entry
        if not state.armed:
            state.armed = True
            if self.window_s > 0:
                loop.call_later(self.window_s, self._fire, shard)
            else:
                loop.call_soon(self._fire, shard)
        return entry.future

    def _fire(self, shard: int) -> None:
        state = self._shards.get(shard)
        if state is None or not state.pending:
            if state is not None:
                state.armed = False
            return
        batch = state.pending
        state.pending = {}
        state.armed = False
        self.sweeps += 1
        asyncio.ensure_future(self._sweep(batch))

    async def _sweep(self, batch: dict) -> None:
        """One confirmation round over every group in ``batch``: one
        zero-entry envelope per destination peer, per-group majority
        counted from the aligned reply items."""
        need: dict = {}      # group_id -> acks still needed
        acks: dict = {}      # group_id -> acks seen
        # destination peer id -> list of (group_id, AppendEntriesRequest)
        by_dest: dict = {}
        # placement steering: peers to deprioritize as confirmation
        # targets this sweep (empty set on the default paths)
        avoid = self.server.read_steering.avoided()
        for gid, entry in batch.items():
            div = entry.division
            if div.leader_ctx is None:
                if not entry.future.done():
                    entry.future.set_exception(
                        ReadIndexException("not leader"))
                continue
            conf = div.state.configuration
            others = [p for p in conf.voting_peers()
                      if p.id != div.member_id.peer_id]
            if not others:
                self._resolve(batch, gid)
                continue
            need[gid] = len(conf.voting_peers()) // 2 + 1 - 1  # minus self
            acks[gid] = 0
            if avoid:
                # skip steered (grey/laggy) peers only while the
                # remaining voters can still reach this group's majority
                preferred = [p for p in others if str(p.id) not in avoid]
                if len(preferred) >= need[gid]:
                    self.server.read_steering.steered += \
                        len(others) - len(preferred)
                    others = preferred
            log = div.state.log
            prev = log.get_last_entry_term_index()
            commit = log.get_last_committed_index()
            for peer in others:
                req = AppendEntriesRequest(
                    RaftRpcHeader(div.member_id.peer_id, peer.id, gid),
                    div.state.current_term, prev, (), commit)
                by_dest.setdefault(peer.id, []).append((gid, req))
        for dest, items in by_dest.items():
            name = str(dest)
            self.confirm_sent[name] = \
                self.confirm_sent.get(name, 0) + len(items)

        async def _send(dest, items) -> None:
            env = AppendEnvelope(tuple(req for _, req in items))
            try:
                reply = await self.server.send_server_rpc(dest, env)
            except Exception:
                return
            if reply is None or reply.status != 0 or not reply.items:
                return
            for (gid, _), item in zip(items, reply.items):
                if item is None or gid not in need:
                    continue
                if item.result == AppendResult.SUCCESS \
                        or item.result == AppendResult.INCONSISTENCY:
                    acks[gid] += 1
                    if acks[gid] >= need[gid]:
                        need.pop(gid, None)
                        self._resolve(batch, gid)

        tasks = [asyncio.create_task(_send(dest, items))
                 for dest, items in by_dest.items()]
        if tasks:
            try:
                await asyncio.wait(tasks, timeout=self.timeout_s)
            finally:
                for t in tasks:
                    t.cancel()
        for gid in list(need):
            entry = batch[gid]
            if not entry.future.done():
                entry.future.set_exception(ReadIndexException(
                    f"leadership not confirmed: "
                    f"{acks.get(gid, 0)} acks short of majority"))

    def _resolve(self, batch: dict, gid) -> None:
        entry = batch[gid]
        if not entry.future.done():
            entry.future.set_result(None)
            self.confirmed += entry.waiters
