"""Production serving plane: admission control + batched linearizable reads.

Two halves, one per module, configured by ``raft.tpu.serving.*``
(RaftServerConfigKeys.Serving):

- admission (serving.admission): per-loop-shard bounded pending budgets
  (count + bytes) enforced at client intake, before the request hops to a
  division loop.  Overflow is shed with a typed
  ResourceUnavailableException carrying a retry-after hint, so a
  saturated shard degrades into fast typed rejections instead of a p99
  collapse.  The check lives in RaftServer._handle_client_request — the
  single intake every transport (TCP, gRPC, simulated) funnels through —
  so the typed reply crosses all three wires identically.

- batched reads (serving.readbatch): one cross-group readIndex
  leadership-confirmation sweep per shard, riding the replication lane
  protocol as zero-entry unsequenced append envelopes, amortizing the
  per-group heartbeat round the same way the quorum engine amortizes
  per-group math.  The leader-lease fast path in Division's
  _leader_read_index still skips the round entirely while the lease
  holds; the scheduler only sees reads that actually need confirmation.

The plane registers a ``serving_plane`` metric registry (sheddedRequests,
per-shard pending gauges, confirmation sweep counters) mirroring the
replication plane's registry, and feeds the watchdog's sustained-overload
detection and the telemetry sampler's shed counter.
"""

from __future__ import annotations

from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.server.serving.admission import AdmissionController
from ratis_tpu.server.serving.readbatch import ReadIndexScheduler

__all__ = ["ServingPlane", "AdmissionController", "ReadIndexScheduler"]


class ServingPlane:
    """Per-server serving-plane root: owns the admission controller and
    the batched-read scheduler, and their shared metric registry."""

    def __init__(self, server) -> None:
        self.server = server
        p = server.properties
        self.admission = AdmissionController(server)
        self.read_batch = (ReadIndexScheduler(server)
                           if RaftServerConfigKeys.Serving.read_batch_enabled(p)
                           else None)
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo, labeled)
        self._registry_info = MetricRegistryInfo(
            prefix=str(server.peer_id), application="ratis",
            component="server", name="serving_plane")
        plane = MetricRegistries.global_registries().create(self._registry_info)
        adm = self.admission
        plane.gauge("sheddedRequests", lambda: adm.shed_total)
        plane.gauge("admittedRequests", lambda: adm.admitted_total)
        for i in range(adm.n_shards):
            plane.gauge(labeled("servingPendingCount", shard=i),
                        lambda s=i: adm.pending_count[s])
            plane.gauge(labeled("servingPendingBytes", shard=i),
                        lambda s=i: adm.pending_bytes[s])
        if self.read_batch is not None:
            rb = self.read_batch
            plane.gauge("readConfirmSweeps", lambda: rb.sweeps)
            plane.gauge("readConfirmBatchedReads", lambda: rb.confirmed)

    def close(self) -> None:
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self._registry_info)
