"""Event-loop pause monitor.

Capability parity with the reference JvmPauseMonitor
(ratis-common/src/main/java/org/apache/ratis/util/JvmPauseMonitor.java:38,145,
wired per-server at RaftServerProxy.java:243): a sentinel sleeps for a short
interval and measures how late it wakes.  In the JVM the deviation exposes GC
stop-the-world pauses; here it exposes anything that stalls the asyncio loop
— a synchronous XLA compile, GIL-holding native code, CPU starvation.

A stalled loop cannot send heartbeats, so its leaderships are already dying
at the followers; detecting the pause locally lets the server abdicate
immediately (via the same leadership-stale path the engine uses) instead of
serving stale reads or holding client requests it can no longer commit —
the reference handler's leader.stepDown on pause > election timeout.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

LOG = logging.getLogger(__name__)


class PauseMonitor:
    def __init__(self, server, interval_s: Optional[float] = None,
                 warn_s: Optional[float] = None,
                 stepdown_s: Optional[float] = None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        self.server = server
        p = server.properties
        keys = RaftServerConfigKeys.PauseMonitor
        self.interval_s = (interval_s if interval_s is not None
                           else keys.interval(p).seconds)
        self.warn_s = warn_s if warn_s is not None \
            else keys.warn_threshold(p).seconds
        # Default step-down threshold: the engine's leadership-staleness
        # window (2x max election timeout, floored at 1s so ordinary loop
        # queueing under load never abdicates) — a pause that long means
        # followers may already be electing a successor.
        self.stepdown_s = (stepdown_s if stepdown_s is not None else max(
            1.0, RaftServerConfigKeys.Rpc.timeout_max(p).seconds * 2))
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self.pause_count = 0
        self.stepdown_count = 0
        self.max_pause_s = 0.0
        # detections in the server registry, not just the log (reference
        # JvmPauseMonitor publishes the same pair through its metrics):
        # numPauses counter + longestPauseMs gauge, scraped at
        # ratis_server_numPauses_total / ratis_server_longestPauseMs.
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo)
        info = MetricRegistryInfo(prefix=str(server.peer_id),
                                  application="ratis", component="server",
                                  name="pause_monitor")
        self.registry = MetricRegistries.global_registries().create(info)
        self.num_pauses = self.registry.counter("numPauses")
        self.num_stepdowns = self.registry.counter("numStepDowns")
        self.registry.gauge("longestPauseMs",
                            lambda: round(self.max_pause_s * 1e3, 3))

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(
            self._run(), name=f"pause-monitor-{self.server.peer_id}")

    async def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self.registry.info)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while self._running:
            t0 = loop.time()
            await asyncio.sleep(self.interval_s)
            pause = loop.time() - t0 - self.interval_s
            if pause <= self.warn_s:
                continue
            self.pause_count += 1
            self.num_pauses.inc()
            self.max_pause_s = max(self.max_pause_s, pause)
            LOG.warning("%s: event loop paused ~%.0fms (threshold %.0fms)",
                        self.server.peer_id, pause * 1e3, self.warn_s * 1e3)
            if pause > self.stepdown_s:
                await self._step_down_leaders(pause)

    async def _step_down_leaders(self, pause: float) -> None:
        for div in list(self.server.divisions.values()):
            if div.is_leader():
                self.stepdown_count += 1
                self.num_stepdowns.inc()
                await div.change_to_follower(
                    div.state.current_term, None,
                    reason=f"event loop paused {pause * 1e3:.0f}ms, beyond "
                           f"the election timeout")
