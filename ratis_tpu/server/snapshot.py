"""Snapshot transfer: leader-side chunking + follower-side installation.

Capability parity with the reference snapshot path:
- Leader: InstallSnapshotRequests chunk iterator bounded by chunk size
  (ratis-server/.../leader/InstallSnapshotRequests.java) and the
  notification mode for app-managed state transfer
  (GrpcLogAppender.notifyInstallSnapshot:805).
- Follower: SnapshotInstallationHandler + SnapshotManager
  (ratis-server/.../impl/SnapshotInstallationHandler.java:60,
  storage/SnapshotManager.java): MD5-verified chunks staged in tmp/,
  renamed into sm/, the StateMachine paused + reinitialized, the local log
  restarted above the snapshot.
"""

from __future__ import annotations

import asyncio
import hashlib
import pathlib
import time
from typing import AsyncIterator, Optional

from ratis_tpu.protocol.exceptions import InstallSnapshotException
from ratis_tpu.protocol.raftrpc import (FileChunk, InstallSnapshotReply,
                                        InstallSnapshotRequest,
                                        InstallSnapshotResult, RaftRpcHeader)
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.server.statemachine import SnapshotInfo


def file_md5(path: pathlib.Path) -> bytes:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.digest()


class SnapshotInstaller:
    """Follower-side receiver: stages chunks in tmp/, verifies MD5, commits
    into the SM storage directory."""

    def __init__(self, division):
        self.division = division
        self._staging: dict[str, object] = {}  # filename -> open file
        self._verified: set[str] = set()  # files completed+MD5-checked
        self._in_progress_index: int = -1

    @property
    def in_progress_index(self) -> int:
        return self._in_progress_index

    def _tmp_path(self, filename: str) -> pathlib.Path:
        div = self.division
        base = (div.storage.tmp_dir if div.storage is not None
                else pathlib.Path("/tmp"))
        base.mkdir(parents=True, exist_ok=True)
        return base / (filename + ".install")

    async def receive(self, req: InstallSnapshotRequest) -> InstallSnapshotResult:
        div = self.division
        ti = req.snapshot_term_index
        if ti is None:
            return InstallSnapshotResult.CONF_MISMATCH
        current = div.state_machine.get_latest_snapshot()
        if current is not None and current.index >= ti.index:
            return InstallSnapshotResult.ALREADY_INSTALLED
        if self._in_progress_index != ti.index:
            # New install (possibly after an aborted one): drop stale staging
            # so unverified partials never reach the SM directory.
            self._abort_staging()
            self._in_progress_index = ti.index

        for chunk in req.chunks:
            tmp = self._tmp_path(chunk.filename)
            f = self._staging.get(chunk.filename)
            if f is None:
                f = open(tmp, "wb")
                self._staging[chunk.filename] = f
            if f.tell() != chunk.offset:
                f.seek(chunk.offset)
            f.write(chunk.data)
            if chunk.done:
                f.close()
                del self._staging[chunk.filename]
                if chunk.file_digest and file_md5(tmp) != chunk.file_digest:
                    tmp.unlink(missing_ok=True)
                    self._in_progress_index = -1
                    raise InstallSnapshotException(
                        f"MD5 mismatch for snapshot file {chunk.filename}")
                self._verified.add(chunk.filename)

        if not req.done:
            return InstallSnapshotResult.IN_PROGRESS
        await self._commit(ti)
        return InstallSnapshotResult.SUCCESS

    def _abort_staging(self) -> None:
        for f in self._staging.values():
            try:
                f.close()
            except Exception:
                pass
        self._staging.clear()
        self._verified.clear()
        div = self.division
        base = (div.storage.tmp_dir if div.storage is not None
                else pathlib.Path("/tmp"))
        if base.exists():
            for tmp in base.glob("*.install"):
                tmp.unlink(missing_ok=True)

    async def _commit(self, ti: TermIndex) -> None:
        div = self.division
        sm = div.state_machine
        storage = sm.get_state_machine_storage()
        sm_dir = storage.directory
        if sm_dir is None:
            raise InstallSnapshotException("state machine has no storage dir")
        await sm.pause()
        try:
            base = (div.storage.tmp_dir if div.storage is not None
                    else pathlib.Path("/tmp"))
            # Promote ONLY files completed and MD5-verified in this install;
            # leftovers from aborted installs stay out of sm/.
            for name in self._verified:
                tmp = base / (name + ".install")
                if tmp.exists():
                    tmp.replace(sm_dir / name)
            await sm.reinitialize()
        finally:
            self._verified.clear()
            self._in_progress_index = -1
        # Local log restarts just above the installed snapshot
        # (reference SnapshotInstallationHandler pause/reload + log purge).
        div.state.log.set_snapshot_boundary(ti)
        div.set_applied_index(ti.index)
        await sm.notify_snapshot_installed(
            SnapshotInfo(ti), div.member_id.peer_id)


class SnapshotSender:
    """Leader-side driver: streams chunk batches to one follower, or sends
    the notification when file transfer is disabled."""

    def __init__(self, division, chunk_size: int = 16 << 20,
                 install_enabled: bool = True):
        self.division = division
        self.chunk_size = chunk_size
        self.install_enabled = install_enabled

    async def send_to(self, follower) -> bool:
        """Returns True if the follower was advanced (nextIndex bumped)."""
        div = self.division
        snapshot = div.state_machine.get_latest_snapshot()
        header = RaftRpcHeader(div.member_id.peer_id, follower.peer_id,
                               div.group_id)

        if not self.install_enabled or snapshot is None:
            first = div.state.log.get_term_index(div.state.log.start_index) \
                or TermIndex(div.state.current_term, div.state.log.start_index)
            req = InstallSnapshotRequest(
                header, div.state.current_term,
                notification_first_available=first,
                last_included=snapshot.term_index if snapshot else None)
            reply = await div.server.send_server_rpc(follower.peer_id, req)
            if reply.result in (InstallSnapshotResult.SUCCESS,
                                InstallSnapshotResult.ALREADY_INSTALLED,
                                InstallSnapshotResult.SNAPSHOT_INSTALLED) \
                    and reply.snapshot_index >= 0:
                follower.next_index = max(follower.next_index,
                                          reply.snapshot_index + 1)
                return True
            return False

        # Stream chunk batches straight from disk — never materialize the
        # whole snapshot in memory (one read per request, like the reference
        # FileChunkReader).
        files = [pathlib.Path(fi.path) for fi in snapshot.files]
        digests = {p.name: (fi.digest or await asyncio.to_thread(file_md5, p))
                   for p, fi in zip(files, snapshot.files)}
        request_index = 0
        for fidx, path in enumerate(files):
            total = path.stat().st_size
            offset = 0
            chunk_idx = 0
            with open(path, "rb") as f:
                while True:
                    data = await asyncio.to_thread(f.read, self.chunk_size)
                    file_done = offset + len(data) >= total
                    last_file = fidx == len(files) - 1
                    chunk = FileChunk(
                        filename=path.name, total_size=total,
                        file_digest=digests[path.name],
                        chunk_index=chunk_idx, offset=offset, data=data,
                        done=file_done)
                    req = InstallSnapshotRequest(
                        header, div.state.current_term,
                        request_id=str(div.member_id),
                        request_index=request_index,
                        snapshot_term_index=snapshot.term_index,
                        chunks=(chunk,), total_size=total,
                        done=file_done and last_file)
                    request_index += 1
                    reply = await div.server.send_server_rpc(
                        follower.peer_id, req)
                    # A chunk reply is proof of life: refresh the response
                    # clock so slowness detection doesn't fire mid-install.
                    follower.last_rpc_response_s = time.monotonic()
                    if reply.result == InstallSnapshotResult.ALREADY_INSTALLED:
                        follower.next_index = max(follower.next_index,
                                                  snapshot.index + 1)
                        return True
                    if reply.result not in (InstallSnapshotResult.SUCCESS,
                                            InstallSnapshotResult.IN_PROGRESS):
                        return False
                    offset += len(data)
                    chunk_idx += 1
                    if file_done:
                        break
        follower.next_index = max(follower.next_index, snapshot.index + 1)
        follower.update_match(snapshot.index)
        return True
