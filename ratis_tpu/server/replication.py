"""Server-level replication fan-out: one sender per destination server.

The reference runs one LogAppender daemon per (group, follower), each with
its own long-lived stream (ratis-grpc/.../server/GrpcLogAppender.java:70,
343-381) — O(groups) threads and O(groups) RPC streams toward every peer.
That cost shape is exactly what caps the multi-raft axis at thousands of
co-hosted groups.

This module keeps the per-follower window/epoch state machine
(ratis_tpu.server.leader.LogAppender) but replaces the send fabric: ONE
PeerSender task per destination server drains every marked appender's
window fills into a single :class:`AppendEnvelope` RPC per flush (data-path
coalescing), or into a concurrent burst of unary RPCs when coalescing is
disabled (the reference's per-group cost shape, kept as the benchmark
baseline mode).

Ordering: per-group FIFO holds end to end because (a) an appender
contributes items to at most one in-flight envelope at a time (the
``collect``/``envelope_done`` busy latch), (b) envelopes carry items in
collect order, and (c) the receiver (RaftServer._handle_append_envelope)
processes one group's items sequentially in order.  Reordering across those
guarantees (e.g. unary mode over a reordering transport) at worst costs a
spurious INCONSISTENCY + window reset — never safety, because match only
advances from request-capped SUCCESS confirmations.
"""

from __future__ import annotations

import asyncio
import logging
from typing import NamedTuple, Optional

from ratis_tpu.metrics.hops import hop
from ratis_tpu.protocol.exceptions import TimeoutIOException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest, AppendEnvelope,
                                        AppendResult)

LOG = logging.getLogger(__name__)


class _LoopSweep:
    """Per-(event-loop) sweep state: the senders marked due on that loop
    and whether a drain pass is already scheduled.  Only ever touched from
    its own loop's thread."""

    __slots__ = ("due", "armed")

    def __init__(self) -> None:
        self.due: dict["PeerSender", None] = {}
        self.armed = False


class OutItem(NamedTuple):
    """One collected AppendEntries send: who to notify and with which epoch
    the reply must be matched (stale-epoch replies are dropped by the
    appender, mirroring GrpcLogAppender's resetClient semantics)."""

    appender: object  # leader.LogAppender
    request: AppendEntriesRequest
    epoch: int
    pipelined: bool


class PeerSender:
    """Drains every co-hosted group's pending append batches toward ONE
    destination server.

    A flush collects from all marked appenders (round-robin in mark order,
    bounded by the envelope byte budget) and ships one envelope; up to
    ``inflight_cap`` envelopes may be in flight so one slow envelope never
    head-of-line-blocks other groups' batches.  While an envelope is in
    flight its appenders are latched busy, so a group's entries are never
    split across two racing envelopes.
    """

    def __init__(self, server, to: RaftPeerId, *, coalescing: bool,
                 inflight_cap: int, envelope_byte_limit: int,
                 metrics: Optional[dict] = None, sweep: bool = False,
                 scheduler: "Optional[ReplicationScheduler]" = None):
        self.server = server
        self.to = to
        self.coalescing = coalescing
        self.envelope_byte_limit = envelope_byte_limit
        self.metrics = metrics if metrics is not None else {
            "envelopes": 0, "items": 0, "rewinds": 0}
        self._dirty: dict[object, None] = {}  # insertion-ordered appender set
        self.refs: set = set()  # registered appenders (scheduler-managed)
        # the loop this sender (and every appender feeding it) lives on:
        # with loop sharding there is one sender per (destination, shard),
        # and the scheduler's close() must unwind it on this loop
        self.loop = asyncio.get_running_loop()
        # Sweep mode (raft.tpu.replication.sweep): NO standing flush-loop
        # task — marks register this sender with the scheduler's per-loop
        # sweep, and one scheduled drain pass collects across every due
        # sender on the loop.  sweep=0 keeps the per-sender wake-event
        # flush loop exactly as before.
        self.sweep = sweep
        self.scheduler = scheduler
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        if sweep:
            self._slots = None
            self._slots_free = max(1, inflight_cap)
        else:
            self._slots = asyncio.Semaphore(max(1, inflight_cap))
            self._slots_free = 0
        self._running = True
        self._inflight_tasks: set[asyncio.Task] = set()
        if not sweep:
            self._task = asyncio.create_task(
                self._run(), name=f"sender-{server.peer_id}->{to}")

    # -- intake ---------------------------------------------------------------

    def mark(self, appender) -> None:
        """Register an appender as having (potential) work toward this
        destination and wake the flush loop (legacy) or arm the loop's
        cross-group sweep pass (sweep mode)."""
        self._dirty[appender] = None
        if self.sweep:
            if self._running:
                self.scheduler.arm_sweep(self)
        else:
            if not self._wake.is_set():
                hop("sender_wake")
            self._wake.set()

    def unmark(self, appender) -> None:
        self._dirty.pop(appender, None)

    # -- sweep mode: scheduler-driven drain pass ------------------------------

    def sweep_collect(self) -> None:
        """One drain pass over this sender's dirty appenders (called from
        the scheduler's per-loop sweep).  Collects multi-group envelopes
        until the dirty set or the in-flight slots run out; with the
        in-flight cap reached, the remaining dirty appenders keep their
        marks and the slot release re-arms the sweep."""
        server = self.server
        while self._running and self._dirty and self._slots_free > 0:
            items: list[OutItem] = []
            budget = self.envelope_byte_limit
            while self._dirty and budget > 0:
                a = next(iter(self._dirty))
                del self._dirty[a]
                try:
                    budget -= a.collect(items, budget)
                except Exception:
                    LOG.exception("%s->%s collect failed for %s",
                                  server.peer_id, self.to, a)
            if not items:
                return
            self.metrics["envelopes"] += 1
            self.metrics["items"] += len(items)
            if self.coalescing:
                self._slots_free -= 1
                t = asyncio.create_task(self._send(items))
                self._inflight_tasks.add(t)
                t.add_done_callback(self._inflight_tasks.discard)
            else:
                # reference cost shape, swept: the drain pass is shared but
                # each collected batch still ships as its own unary RPC
                # with per-reply window refill (see _run's unary branch)
                for it in items:
                    it.appender.envelope_done(remark=False)
                    t = asyncio.create_task(self._send_unary(it))
                    self._inflight_tasks.add(t)
                    t.add_done_callback(self._inflight_tasks.discard)

    def _release_slot(self) -> None:
        if self.sweep:
            self._slots_free += 1
            if self._dirty and self._running:
                self.scheduler.arm_sweep(self)
        else:
            self._slots.release()

    # -- flush loop -----------------------------------------------------------

    async def _run(self) -> None:
        server = self.server
        while self._running:
            if not self._dirty:
                self._wake.clear()
                if not self._dirty:  # re-check: mark may race the clear
                    await self._wake.wait()
                # Micro-batch: let the in-progress scheduling burst (many
                # groups appending in the same loop pass) finish marking
                # before collecting, so the burst folds into one envelope
                # instead of a first tiny one + a big one.
                await asyncio.sleep(0)
                continue
            await self._slots.acquire()
            if not self._running:
                self._slots.release()
                return
            items: list[OutItem] = []
            budget = self.envelope_byte_limit
            while self._dirty and budget > 0:
                a = next(iter(self._dirty))
                del self._dirty[a]
                try:
                    budget -= a.collect(items, budget)
                except Exception:
                    LOG.exception("%s->%s collect failed for %s",
                                  server.peer_id, self.to, a)
            if not items:
                self._slots.release()
                continue
            self.metrics["envelopes"] += 1
            self.metrics["items"] += len(items)
            if self.coalescing:
                t = asyncio.create_task(self._send(items))
                self._inflight_tasks.add(t)
                t.add_done_callback(self._inflight_tasks.discard)
            else:
                # Reference cost shape: one independent unary RPC task per
                # batch, window refilled per reply — NO flush barrier, so
                # this baseline mode keeps exactly the old per-appender
                # pipelining behavior (a slow RPC never stalls the rest of
                # the flush's items, and the benchmark's vs_baseline
                # compares against an unhandicapped per-group path).
                for it in items:
                    it.appender.envelope_done(remark=False)
                    t = asyncio.create_task(self._send_unary(it))
                    self._inflight_tasks.add(t)
                    t.add_done_callback(self._inflight_tasks.discard)
                self._slots.release()

    async def _send_unary(self, it: OutItem) -> None:
        """Baseline (coalescing-disabled) path: one RPC per collected batch,
        reply dispatched independently — the reference's per-(group,
        follower) send shape."""
        try:
            reply = await self.server.send_server_rpc(self.to, it.request)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            it.appender.on_send_error(it, e)
            return
        try:
            await it.appender.on_send_reply(it, reply)
        except Exception:
            LOG.exception("%s->%s unary reply dispatch failed",
                          self.server.peer_id, self.to)
        finally:
            it.appender.notify()  # refill the window per reply
            if not self.sweep:
                self._wake.set()

    async def _send(self, items: list[OutItem]) -> None:
        server = self.server
        replies: list = []
        error: Optional[Exception] = None
        remark = True
        # Packed ack intake (sweep mode): every SUCCESS reply in this
        # envelope contributes one [slot, peer_slot, match] row here
        # instead of a scalar QuorumEngine.on_ack call, and the whole
        # frame batch enters the engine under ONE intake-lock round-trip.
        ack_rows: Optional[list] = [] if self.sweep else None
        # One outer try/finally owns the latch + slot: ANY exit (including
        # cancellation from a source other than close(), which used to skip
        # the slot release and wedge the sender after inflight_cap events)
        # releases the envelope slot and the appenders' busy latch.
        try:
            try:
                if len(items) > 1:
                    env = AppendEnvelope(tuple(it.request for it in items))
                    reply = await server.send_server_rpc(self.to, env)
                    replies = list(reply.items)
                    if len(replies) != len(items):
                        raise TimeoutIOException(
                            "envelope reply length mismatch")
                else:
                    replies = [await server.send_server_rpc(
                        self.to, items[0].request)]
            except asyncio.CancelledError:
                remark = False
                raise
            except Exception as e:
                error = e
            for i, it in enumerate(items):
                rep = error if error is not None else replies[i]
                try:
                    if isinstance(rep, asyncio.CancelledError):
                        continue
                    if rep is None:
                        rep = TimeoutIOException(
                            f"{self.to} failed this group's append")
                    if ack_rows and (isinstance(rep, Exception)
                                     or rep.result != AppendResult.SUCCESS):
                        # Ordering guard: a non-SUCCESS dispatch can
                        # REGRESS a follower's match (INCONSISTENCY after
                        # a volatile-log restart, via regress_match) — the
                        # rows buffered so far must enter the engine FIRST
                        # or the later batch apply would scatter-max a
                        # stale ack back over the regression.  Exactly the
                        # scalar path's interleaving, batched between
                        # anomalies (which are rare on the hot path).
                        server.engine.on_ack_batch(ack_rows)
                        ack_rows = []
                    if isinstance(rep, Exception):
                        it.appender.on_send_error(it, rep)
                    else:
                        await it.appender.on_send_reply(it, rep, ack_rows)
                except Exception:
                    LOG.exception("%s->%s reply dispatch failed",
                                  server.peer_id, self.to)
            if ack_rows:
                server.engine.on_ack_batch(ack_rows)
        finally:
            for a in {it.appender for it in items}:
                a.envelope_done(remark=remark)
            self._release_slot()
            if not self.sweep:
                self._wake.set()

    async def close(self) -> None:
        self._running = False
        self._wake.set()
        # close() can be reached from INSIDE one of this sender's own
        # inflight _send tasks (reply dispatch -> change_to_follower ->
        # appender.stop -> scheduler.release): never cancel-and-await the
        # task we are currently running in.
        cur = asyncio.current_task()
        tasks = [t for t in (self._task, *self._inflight_tasks)
                 if t is not None and t is not cur]
        self._inflight_tasks.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass


class ReplicationScheduler:
    """Owns one PeerSender per destination this server replicates toward
    (created lazily; peers are few even when groups are many)."""

    def __init__(self, server, *, coalescing: bool, inflight_cap: int,
                 envelope_byte_limit: int, sweep: bool = False):
        self.server = server
        self.coalescing = coalescing
        self.inflight_cap = inflight_cap
        self.envelope_byte_limit = envelope_byte_limit
        # Cross-group append sweeps (raft.tpu.replication.sweep): marks
        # arm ONE drain pass per (loop, burst) that collects due
        # AppendEntries across every destination's dirty appenders on
        # that loop, replacing the per-sender wake->collect->schedule
        # flush-loop wakeups.  Off (0) = the per-request legacy path.
        self.sweep = sweep
        # loop key -> _LoopSweep; each entry is only touched from its own
        # loop's thread (marks and drain passes are loop-affine)
        self._sweeps: dict[int, _LoopSweep] = {}
        # keyed by (destination, calling loop): with loop sharding each
        # shard gets its own sender per destination — its flush task and
        # outbound connection live on the shard's loop, so one shard's
        # flush never queues behind another's (unsharded: one loop, one
        # sender per destination, exactly the old shape)
        self._senders: dict[tuple, PeerSender] = {}
        self._closed = False
        # shared across senders: folding evidence for tests/benchmarks;
        # "rewinds" counts INCONSISTENCY-triggered window resets (the
        # reorder churn the keyed-FIFO gRPC dispatch exists to prevent —
        # ADVICE r5; incremented by LogAppender._on_reply)
        self.metrics = {"envelopes": 0, "items": 0, "rewinds": 0}

    @staticmethod
    def codec_stats() -> dict:
        """Snapshot of the encode-once fast path's counters
        (protocol.raftrpc.FANOUT_STATS): how often the spliced append
        encoder ran, how often a fan-out suffix was reused, and whether
        anything fell back to the generic packer."""
        from ratis_tpu.protocol.raftrpc import FANOUT_STATS
        return dict(FANOUT_STATS)

    @staticmethod
    def _loop_key() -> int:
        try:
            return id(asyncio.get_running_loop())
        except RuntimeError:
            return 0

    def sender_for(self, to: RaftPeerId) -> PeerSender:
        key = (to, self._loop_key())
        s = self._senders.get(key)
        if s is None:
            if self._closed:
                raise RuntimeError("replication scheduler closed")
            s = PeerSender(self.server, to, coalescing=self.coalescing,
                           inflight_cap=self.inflight_cap,
                           envelope_byte_limit=self.envelope_byte_limit,
                           metrics=self.metrics, sweep=self.sweep,
                           scheduler=self)
            self._senders[key] = s
        return s

    # -- sweep mode: one drain pass per (loop, burst) -------------------------

    def arm_sweep(self, sender: PeerSender) -> None:
        """Register ``sender`` as due and schedule at most ONE drain pass
        on its loop for the current scheduling burst.  All marks issued in
        the same event-loop pass — however many groups and destinations —
        fold into that single callback; call_soon runs it after the
        in-progress burst finishes marking, the same micro-batching the
        per-sender flush loop got from its post-wake ``sleep(0)``."""
        key = self._loop_key()
        st = self._sweeps.get(key)
        if st is None:
            st = self._sweeps[key] = _LoopSweep()
        st.due[sender] = None
        if not st.armed:
            st.armed = True
            hop("sender_wake")
            sender.loop.call_soon(self._sweep_pass, st)

    def _sweep_pass(self, st: _LoopSweep) -> None:
        st.armed = False
        due, st.due = st.due, {}
        for sender in due:
            try:
                sender.sweep_collect()
            except Exception:
                LOG.exception("replication sweep pass failed for %s",
                              sender.to)

    def acquire(self, to: RaftPeerId, appender) -> PeerSender:
        """sender_for + register ``appender`` as a user; pair with
        :meth:`release` so a sender (and its standing flush-loop task) is
        retired when its last appender goes away under membership churn."""
        s = self.sender_for(to)
        s.refs.add(appender)
        return s

    async def release(self, to: RaftPeerId, appender) -> None:
        # appenders acquire and release on their own (shard) loop, so the
        # loop key resolves to the same sender acquire() returned
        key = (to, self._loop_key())
        s = self._senders.get(key)
        if s is None:
            return
        s.refs.discard(appender)
        s.unmark(appender)
        if not s.refs:
            self._senders.pop(key, None)
            await s.close()

    async def close(self) -> None:
        self._closed = True
        senders = list(self._senders.values())
        self._senders.clear()
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        for s in senders:
            if s.loop is current:
                await s.close()
            elif s.loop.is_running():
                # shard-owned sender: unwind it on its own loop (its tasks
                # and wake event are loop-affine)
                try:
                    await asyncio.wrap_future(
                        asyncio.run_coroutine_threadsafe(s.close(), s.loop))
                except Exception:
                    LOG.exception("cross-loop sender close failed for %s",
                                  s.to)
            else:
                # owner loop already gone (test teardown): its tasks can
                # never resume — best-effort cancel, nothing to await
                s._running = False
                for t in (s._task, *s._inflight_tasks):
                    if t is not None:
                        t.cancel()
                s._inflight_tasks.clear()
