"""Server-level replication fan-out: one sender per destination server.

The reference runs one LogAppender daemon per (group, follower), each with
its own long-lived stream (ratis-grpc/.../server/GrpcLogAppender.java:70,
343-381) — O(groups) threads and O(groups) RPC streams toward every peer.
That cost shape is exactly what caps the multi-raft axis at thousands of
co-hosted groups.

This module keeps the per-follower window/epoch state machine
(ratis_tpu.server.leader.LogAppender) but replaces the send fabric: ONE
PeerSender task per destination server drains every marked appender's
window fills into a single :class:`AppendEnvelope` RPC per flush (data-path
coalescing), or into a concurrent burst of unary RPCs when coalescing is
disabled (the reference's per-group cost shape, kept as the benchmark
baseline mode).

Ordering: per-group FIFO holds end to end because (a) a group contributes
items to a bounded window of consecutive in-flight frames
(``raft.tpu.replication.window-depth``; depth 1 degenerates to the
one-envelope-at-a-time busy latch), (b) envelopes carry items in collect
order and sequenced frames carry monotonically numbered (lane, seq) pairs,
and (c) the receiver (RaftServer._handle_append_envelope) processes a
lane's frames strictly in sequence and one group's items sequentially in
envelope order.  With depth > 1 the round trip is PIPELINED: the next
frame is cut from the speculatively-advanced next-index while earlier
frames are still in flight, so a commit no longer pays a full RTT of dead
time per group (reference: GrpcLogAppender.java:343-381's per-follower
sliding window, here batched across groups).  Reordering across those
guarantees (e.g. unary mode over a reordering transport) at worst costs a
spurious INCONSISTENCY + windowed rewind — never safety, because match
only advances from request-capped SUCCESS confirmations.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
from typing import NamedTuple, Optional

from ratis_tpu.metrics.hops import hop
from ratis_tpu.protocol.exceptions import TimeoutIOException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.raftrpc import (ENV_OK, AppendEntriesRequest,
                                        AppendEnvelope, AppendResult)

LOG = logging.getLogger(__name__)

# Lane ids are unique per PeerSender LIFETIME (a restarted/recreated sender
# never reuses its predecessor's sequence space at the receiver) and across
# co-hosted processes dialing the same peer under one requestor id after a
# restart (the pid component).
_LANE_IDS = itertools.count(1)
_LANE_BASE = (os.getpid() & 0x7FFFF) << 32


def _new_lane_id() -> int:
    return _LANE_BASE | next(_LANE_IDS)


class _LoopSweep:
    """Per-(event-loop) sweep state: the senders marked due on that loop
    and whether a drain pass is already scheduled.  Only ever touched from
    its own loop's thread."""

    __slots__ = ("due", "armed")

    def __init__(self) -> None:
        self.due: dict["PeerSender", None] = {}
        self.armed = False


class OutItem(NamedTuple):
    """One collected AppendEntries send: who to notify and with which epoch
    the reply must be matched (stale-epoch replies are dropped by the
    appender, mirroring GrpcLogAppender's resetClient semantics)."""

    appender: object  # leader.LogAppender
    request: AppendEntriesRequest
    epoch: int
    pipelined: bool


class PeerSender:
    """Drains every co-hosted group's pending append batches toward ONE
    destination server.

    A flush collects from all marked appenders (round-robin in mark order,
    bounded by the envelope byte budget) and ships one envelope; up to
    ``inflight_cap`` envelopes may be in flight so one slow envelope never
    head-of-line-blocks other groups' batches.  With
    ``raft.tpu.replication.window-depth`` > 1 (sweep mode + coalescing)
    frames are SEQUENCED on a per-sender lane and a group may ride up to
    depth consecutive in-flight frames — per-group FIFO is enforced by the
    receiver's in-sequence lane intake instead of the busy latch.  Depth 1
    keeps the latch exactly: a group's entries are never split across two
    racing envelopes and frames go out unsequenced (the legacy wire
    shape).
    """

    def __init__(self, server, to: RaftPeerId, *, coalescing: bool,
                 inflight_cap: int, envelope_byte_limit: int,
                 metrics: Optional[dict] = None, sweep: bool = False,
                 scheduler: "Optional[ReplicationScheduler]" = None,
                 window_depth: int = 1):
        self.server = server
        self.to = to
        self.coalescing = coalescing
        self.envelope_byte_limit = envelope_byte_limit
        self.inflight_cap = max(1, inflight_cap)
        # Per-group frame window: only meaningful on the sequenced frame
        # path — sweep + coalescing.  Legacy (sweep=0) and unary modes pin
        # the effective depth at 1 so their paths stay bit-exact.
        self.window_depth = max(1, window_depth)
        self.sequenced = coalescing and sweep and self.window_depth > 1
        self.group_window = self.window_depth if self.sequenced else 1
        if self.sequenced:
            # The lane must hold enough envelope slots for the per-group
            # window to actually fill: with the slot cap at the legacy 4,
            # the depth knob never engages (measured: slots pinned full,
            # occupancy 1.0, zero throughput delta across depths — the
            # envelope window was the binding pipeline, docs/perf.md
            # round 9).  Depth 1 keeps the exact legacy cap.
            self.inflight_cap = min(64,
                                    self.inflight_cap * self.window_depth)
        # lane identity + next frame sequence (sequenced mode): reset to a
        # FRESH lane on any sequenced send failure or receiver reject, so
        # the receiver never waits out a gap that will not fill
        self._lane = _new_lane_id()
        self._seq = 0
        self._frames_out = 0  # envelopes currently in flight (all modes)
        self.metrics = metrics if metrics is not None else {
            "envelopes": 0, "items": 0, "rewinds": 0,
            "windowed_rewinds": 0, "lane_rejects": 0, "lane_resets": 0,
            "win_hwm": 0, "seq_frames": 0}
        self._dirty: dict[object, None] = {}  # insertion-ordered appender set
        self.refs: set = set()  # registered appenders (scheduler-managed)
        # the loop this sender (and every appender feeding it) lives on:
        # with loop sharding there is one sender per (destination, shard),
        # and the scheduler's close() must unwind it on this loop
        self.loop = asyncio.get_running_loop()
        # Sweep mode (raft.tpu.replication.sweep): NO standing flush-loop
        # task — marks register this sender with the scheduler's per-loop
        # sweep, and one scheduled drain pass collects across every due
        # sender on the loop.  sweep=0 keeps the per-sender wake-event
        # flush loop exactly as before.
        self.sweep = sweep
        self.scheduler = scheduler
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        if sweep:
            self._slots = None
            self._slots_free = self.inflight_cap
        else:
            self._slots = asyncio.Semaphore(self.inflight_cap)
            self._slots_free = 0
        self._running = True
        self._inflight_tasks: set[asyncio.Task] = set()
        if not sweep:
            self._task = asyncio.create_task(
                self._run(), name=f"sender-{server.peer_id}->{to}")

    # -- intake ---------------------------------------------------------------

    def mark(self, appender) -> None:
        """Register an appender as having (potential) work toward this
        destination and wake the flush loop (legacy) or arm the loop's
        cross-group sweep pass (sweep mode)."""
        self._dirty[appender] = None
        if self.sweep:
            if self._running:
                self.scheduler.arm_sweep(self)
        else:
            if not self._wake.is_set():
                hop("sender_wake")
            self._wake.set()

    def unmark(self, appender) -> None:
        self._dirty.pop(appender, None)

    # -- sequenced lane bookkeeping -------------------------------------------

    @property
    def frames_in_flight(self) -> int:
        """Envelopes currently awaiting their reply (window-state gauge)."""
        return self._frames_out

    def _next_frame(self) -> tuple[int, int]:
        """(lane, seq) for the envelope being dispatched — assigned in
        collect order on this sender's loop, so lane sequence == intended
        send order; also tracks the in-flight frame count and its
        high-water mark (the bench's window-occupancy artifact)."""
        self._frames_out += 1
        m = self.metrics
        if self._frames_out > m.get("win_hwm", 0):
            m["win_hwm"] = self._frames_out
        if not self.sequenced:
            return 0, -1
        m["seq_frames"] = m.get("seq_frames", 0) + 1
        seq = self._seq
        self._seq += 1
        return self._lane, seq

    def _reset_lane(self) -> None:
        """A sequenced frame failed to reach (or was refused by) the
        receiver: its lane now has a hole that will never fill, so every
        later frame of the lane would be rejected.  Re-cut on a FRESH lane
        — the receiver starts a new in-sequence intake at seq 0 and the
        dead lane's state ages out of its bounded table."""
        if self.sequenced:
            self._lane = _new_lane_id()
            self._seq = 0
            self.metrics["lane_resets"] = \
                self.metrics.get("lane_resets", 0) + 1

    # -- sweep mode: scheduler-driven drain pass ------------------------------

    def sweep_collect(self) -> None:
        """One drain pass over this sender's dirty appenders (called from
        the scheduler's per-loop sweep).  Collects multi-group envelopes
        until the dirty set or the in-flight slots run out; with the
        in-flight cap reached, the remaining dirty appenders keep their
        marks and the slot release re-arms the sweep."""
        server = self.server
        while self._running and self._dirty and self._slots_free > 0:
            items: list[OutItem] = []
            budget = self.envelope_byte_limit
            while self._dirty and budget > 0:
                a = next(iter(self._dirty))
                del self._dirty[a]
                try:
                    got = a.collect(items, budget)
                    budget -= got
                    if got and self.sequenced and a.has_backlog():
                        # the byte budget cut this group's fill short and
                        # its frame window still has room: keep it due so
                        # THIS drain pass cuts its next frame too (the
                        # pipelined fill; gated on progress, so a
                        # backoff/prefault collect can never spin)
                        self._dirty[a] = None
                except Exception:
                    LOG.exception("%s->%s collect failed for %s",
                                  server.peer_id, self.to, a)
            if not items:
                return
            self.metrics["envelopes"] += 1
            self.metrics["items"] += len(items)
            if self.coalescing:
                self._slots_free -= 1
                lane, seq = self._next_frame()
                t = asyncio.create_task(self._send(items, lane, seq))
                self._inflight_tasks.add(t)
                t.add_done_callback(self._inflight_tasks.discard)
            else:
                # reference cost shape, swept: the drain pass is shared but
                # each collected batch still ships as its own unary RPC
                # with per-reply window refill (see _run's unary branch)
                for it in items:
                    it.appender.envelope_done(remark=False)
                    t = asyncio.create_task(self._send_unary(it))
                    self._inflight_tasks.add(t)
                    t.add_done_callback(self._inflight_tasks.discard)

    def _release_slot(self) -> None:
        self._frames_out = max(0, self._frames_out - 1)
        if self.sweep:
            self._slots_free += 1
            if self._dirty and self._running:
                self.scheduler.arm_sweep(self)
        else:
            self._slots.release()

    # -- flush loop -----------------------------------------------------------

    async def _run(self) -> None:
        server = self.server
        while self._running:
            if not self._dirty:
                self._wake.clear()
                if not self._dirty:  # re-check: mark may race the clear
                    await self._wake.wait()
                # Micro-batch: let the in-progress scheduling burst (many
                # groups appending in the same loop pass) finish marking
                # before collecting, so the burst folds into one envelope
                # instead of a first tiny one + a big one.
                await asyncio.sleep(0)
                continue
            await self._slots.acquire()
            if not self._running:
                self._slots.release()
                return
            items: list[OutItem] = []
            budget = self.envelope_byte_limit
            while self._dirty and budget > 0:
                a = next(iter(self._dirty))
                del self._dirty[a]
                try:
                    budget -= a.collect(items, budget)
                except Exception:
                    LOG.exception("%s->%s collect failed for %s",
                                  server.peer_id, self.to, a)
            if not items:
                self._slots.release()
                continue
            self.metrics["envelopes"] += 1
            self.metrics["items"] += len(items)
            if self.coalescing:
                lane, seq = self._next_frame()
                t = asyncio.create_task(self._send(items, lane, seq))
                self._inflight_tasks.add(t)
                t.add_done_callback(self._inflight_tasks.discard)
            else:
                # Reference cost shape: one independent unary RPC task per
                # batch, window refilled per reply — NO flush barrier, so
                # this baseline mode keeps exactly the old per-appender
                # pipelining behavior (a slow RPC never stalls the rest of
                # the flush's items, and the benchmark's vs_baseline
                # compares against an unhandicapped per-group path).
                for it in items:
                    it.appender.envelope_done(remark=False)
                    t = asyncio.create_task(self._send_unary(it))
                    self._inflight_tasks.add(t)
                    t.add_done_callback(self._inflight_tasks.discard)
                self._slots.release()

    async def _send_unary(self, it: OutItem) -> None:
        """Baseline (coalescing-disabled) path: one RPC per collected batch,
        reply dispatched independently — the reference's per-(group,
        follower) send shape."""
        try:
            reply = await self.server.send_server_rpc(self.to, it.request)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            it.appender.on_send_error(it, e)
            return
        try:
            await it.appender.on_send_reply(it, reply)
        except Exception:
            LOG.exception("%s->%s unary reply dispatch failed",
                          self.server.peer_id, self.to)
        finally:
            it.appender.notify()  # refill the window per reply
            if not self.sweep:
                self._wake.set()

    async def _send(self, items: list[OutItem], lane: int = 0,
                    seq: int = -1) -> None:
        server = self.server
        replies: list = []
        error: Optional[Exception] = None
        remark = True
        # Packed ack intake (sweep mode): every SUCCESS reply in this
        # envelope contributes one [slot, peer_slot, match] row here
        # instead of a scalar QuorumEngine.on_ack call, and the whole
        # frame batch enters the engine under ONE intake-lock round-trip.
        ack_rows: Optional[list] = [] if self.sweep else None
        # One outer try/finally owns the latch + slot: ANY exit (including
        # cancellation from a source other than close(), which used to skip
        # the slot release and wedge the sender after inflight_cap events)
        # releases the envelope slot and the appenders' busy latch.
        try:
            try:
                if seq >= 0:
                    # sequenced lane frame: even a single-item flush must
                    # ride the lane — the group may have another frame in
                    # flight, and only the receiver's in-sequence intake
                    # keeps the two ordered
                    env = AppendEnvelope(
                        tuple(it.request for it in items), lane, seq)
                    reply = await server.send_server_rpc(self.to, env)
                    if reply.status != ENV_OK:
                        # the receiver refused the frame unprocessed
                        # (sequence hole / stale duplicate): drop the
                        # lane's unacked frames, re-cut fresh
                        self.metrics["lane_rejects"] = \
                            self.metrics.get("lane_rejects", 0) + 1
                        if lane == self._lane:
                            self._reset_lane()
                        raise TimeoutIOException(
                            f"{self.to} refused lane frame seq={seq} "
                            f"(expects {reply.hint})")
                    replies = list(reply.items)
                    if len(replies) != len(items):
                        raise TimeoutIOException(
                            "envelope reply length mismatch")
                elif len(items) > 1:
                    env = AppendEnvelope(tuple(it.request for it in items))
                    reply = await server.send_server_rpc(self.to, env)
                    replies = list(reply.items)
                    if len(replies) != len(items):
                        raise TimeoutIOException(
                            "envelope reply length mismatch")
                else:
                    replies = [await server.send_server_rpc(
                        self.to, items[0].request)]
            except asyncio.CancelledError:
                remark = False
                raise
            except Exception as e:
                error = e
                if seq >= 0 and lane == self._lane:
                    # the frame may never have reached the receiver: later
                    # frames of this lane would stall on the hole — re-cut
                    self._reset_lane()
            for i, it in enumerate(items):
                rep = error if error is not None else replies[i]
                try:
                    if isinstance(rep, asyncio.CancelledError):
                        continue
                    if rep is None:
                        rep = TimeoutIOException(
                            f"{self.to} failed this group's append")
                    if ack_rows and (isinstance(rep, Exception)
                                     or rep.result != AppendResult.SUCCESS):
                        # Ordering guard: a non-SUCCESS dispatch can
                        # REGRESS a follower's match (INCONSISTENCY after
                        # a volatile-log restart, via regress_match) — the
                        # rows buffered so far must enter the engine FIRST
                        # or the later batch apply would scatter-max a
                        # stale ack back over the regression.  Exactly the
                        # scalar path's interleaving, batched between
                        # anomalies (which are rare on the hot path).
                        server.engine.on_ack_batch(ack_rows)
                        ack_rows = []
                    if isinstance(rep, Exception):
                        it.appender.on_send_error(it, rep)
                    else:
                        await it.appender.on_send_reply(it, rep, ack_rows)
                except Exception:
                    LOG.exception("%s->%s reply dispatch failed",
                                  server.peer_id, self.to)
            if ack_rows:
                server.engine.on_ack_batch(ack_rows)
        finally:
            for a in {it.appender for it in items}:
                a.envelope_done(remark=remark)
            self._release_slot()
            if not self.sweep:
                self._wake.set()

    async def close(self) -> None:
        self._running = False
        self._wake.set()
        # close() can be reached from INSIDE one of this sender's own
        # inflight _send tasks (reply dispatch -> change_to_follower ->
        # appender.stop -> scheduler.release): never cancel-and-await the
        # task we are currently running in.
        cur = asyncio.current_task()
        tasks = [t for t in (self._task, *self._inflight_tasks)
                 if t is not None and t is not cur]
        self._inflight_tasks.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass


class ReplicationScheduler:
    """Owns one PeerSender per destination this server replicates toward
    (created lazily; peers are few even when groups are many)."""

    def __init__(self, server, *, coalescing: bool, inflight_cap: int,
                 envelope_byte_limit: int, sweep: bool = False,
                 window_depth: int = 1):
        self.server = server
        self.coalescing = coalescing
        self.inflight_cap = max(1, inflight_cap)
        self.envelope_byte_limit = envelope_byte_limit
        # Sequenced append-window pipelining
        # (raft.tpu.replication.window-depth): frames-per-group window on
        # every sender; 1 = the latched stop-and-wait-per-group protocol
        self.window_depth = max(1, window_depth)
        # hook: called once per NEW destination (server registers its
        # per-destination window gauges through this)
        self.on_destination = None
        self._known_dests: set[RaftPeerId] = set()
        # Cross-group append sweeps (raft.tpu.replication.sweep): marks
        # arm ONE drain pass per (loop, burst) that collects due
        # AppendEntries across every destination's dirty appenders on
        # that loop, replacing the per-sender wake->collect->schedule
        # flush-loop wakeups.  Off (0) = the per-request legacy path.
        self.sweep = sweep
        # loop key -> _LoopSweep; each entry is only touched from its own
        # loop's thread (marks and drain passes are loop-affine)
        self._sweeps: dict[int, _LoopSweep] = {}
        # keyed by (destination, calling loop): with loop sharding each
        # shard gets its own sender per destination — its flush task and
        # outbound connection live on the shard's loop, so one shard's
        # flush never queues behind another's (unsharded: one loop, one
        # sender per destination, exactly the old shape)
        self._senders: dict[tuple, PeerSender] = {}
        self._closed = False
        # shared across senders: folding evidence for tests/benchmarks;
        # "rewinds" counts INCONSISTENCY-triggered window resets (the
        # reorder churn the keyed-FIFO gRPC dispatch exists to prevent —
        # ADVICE r5; incremented by LogAppender._on_reply);
        # "windowed_rewinds" the subset taken while >1 frame of the group
        # was in flight (the pipelined rewind path); "lane_rejects" /
        # "lane_resets" the sequenced-lane recovery events; "win_hwm" the
        # frames-in-flight high-water mark across senders (bench window
        # occupancy = win_hwm / inflight_cap)
        self.metrics = {"envelopes": 0, "items": 0, "rewinds": 0,
                        "windowed_rewinds": 0, "lane_rejects": 0,
                        "lane_resets": 0, "win_hwm": 0, "seq_frames": 0}

    @staticmethod
    def codec_stats() -> dict:
        """Snapshot of the encode-once fast path's counters
        (protocol.raftrpc.FANOUT_STATS): how often the spliced append
        encoder ran, how often a fan-out suffix was reused, and whether
        anything fell back to the generic packer."""
        from ratis_tpu.protocol.raftrpc import FANOUT_STATS
        return dict(FANOUT_STATS)

    @staticmethod
    def _loop_key() -> int:
        try:
            return id(asyncio.get_running_loop())
        except RuntimeError:
            return 0

    def sender_for(self, to: RaftPeerId) -> PeerSender:
        key = (to, self._loop_key())
        s = self._senders.get(key)
        if s is None:
            if self._closed:
                raise RuntimeError("replication scheduler closed")
            s = PeerSender(self.server, to, coalescing=self.coalescing,
                           inflight_cap=self.inflight_cap,
                           envelope_byte_limit=self.envelope_byte_limit,
                           metrics=self.metrics, sweep=self.sweep,
                           scheduler=self, window_depth=self.window_depth)
            self._senders[key] = s
            if to not in self._known_dests:
                self._known_dests.add(to)
                if self.on_destination is not None:
                    try:
                        self.on_destination(to)
                    except Exception:
                        LOG.exception("on_destination hook failed for %s",
                                      to)
        return s

    # -- window state (gauges / watchdog) -------------------------------------

    @property
    def lane_slots(self) -> int:
        """Envelope slots per (destination, loop-shard) lane — the
        configured inflight cap, scaled by window-depth on the sequenced
        path (matches PeerSender's own computation; the bench's
        window-occupancy denominator)."""
        if self.coalescing and self.sweep and self.window_depth > 1:
            return min(64, self.inflight_cap * self.window_depth)
        return self.inflight_cap

    def frames_in_flight(self, to: Optional[RaftPeerId] = None) -> int:
        """Envelopes in flight toward ``to`` (all destinations when None),
        summed across loop-shard senders."""
        return sum(s.frames_in_flight for (d, _), s in self._senders.items()
                   if to is None or d == to)

    def window_occupancy(self, to: Optional[RaftPeerId] = None) -> float:
        """frames-in-flight / envelope-slot capacity toward ``to``."""
        senders = [s for (d, _), s in self._senders.items()
                   if to is None or d == to]
        cap = sum(s.inflight_cap for s in senders)
        if not cap:
            return 0.0
        return round(sum(s.frames_in_flight for s in senders) / cap, 4)

    # -- sweep mode: one drain pass per (loop, burst) -------------------------

    def arm_sweep(self, sender: PeerSender) -> None:
        """Register ``sender`` as due and schedule at most ONE drain pass
        on its loop for the current scheduling burst.  All marks issued in
        the same event-loop pass — however many groups and destinations —
        fold into that single callback; call_soon runs it after the
        in-progress burst finishes marking, the same micro-batching the
        per-sender flush loop got from its post-wake ``sleep(0)``."""
        key = self._loop_key()
        st = self._sweeps.get(key)
        if st is None:
            st = self._sweeps[key] = _LoopSweep()
        st.due[sender] = None
        if not st.armed:
            st.armed = True
            hop("sender_wake")
            sender.loop.call_soon(self._sweep_pass, st)

    def _sweep_pass(self, st: _LoopSweep) -> None:
        st.armed = False
        due, st.due = st.due, {}
        for sender in due:
            try:
                sender.sweep_collect()
            except Exception:
                LOG.exception("replication sweep pass failed for %s",
                              sender.to)

    def acquire(self, to: RaftPeerId, appender) -> PeerSender:
        """sender_for + register ``appender`` as a user; pair with
        :meth:`release` so a sender (and its standing flush-loop task) is
        retired when its last appender goes away under membership churn."""
        s = self.sender_for(to)
        s.refs.add(appender)
        return s

    async def release(self, to: RaftPeerId, appender) -> None:
        # appenders acquire and release on their own (shard) loop, so the
        # loop key resolves to the same sender acquire() returned
        key = (to, self._loop_key())
        s = self._senders.get(key)
        if s is None:
            return
        s.refs.discard(appender)
        s.unmark(appender)
        if not s.refs:
            self._senders.pop(key, None)
            await s.close()

    async def close(self) -> None:
        self._closed = True
        senders = list(self._senders.values())
        self._senders.clear()
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        for s in senders:
            if s.loop is current:
                await s.close()
            elif s.loop.is_running():
                # shard-owned sender: unwind it on its own loop (its tasks
                # and wake event are loop-affine)
                try:
                    await asyncio.wrap_future(
                        asyncio.run_coroutine_threadsafe(s.close(), s.loop))
                except Exception:
                    LOG.exception("cross-loop sender close failed for %s",
                                  s.to)
            else:
                # owner loop already gone (test teardown): its tasks can
                # never resume — best-effort cancel, nothing to await
                s._running = False
                for t in (s._task, *s._inflight_tasks):
                    if t is not None:
                        t.cancel()
                s._inflight_tasks.clear()
