"""Division: one group member — role machine, RPC handlers, apply loop.

Capability parity with the reference RaftServerImpl
(ratis-server/.../impl/RaftServerImpl.java:155): role transitions
(changeToFollower:587 / changeToLeader:635 / changeToCandidate:706), the
client write path (submitClientRequestAsync:937 -> appendTransaction:820),
reads (readAsync:1058, staleReadAsync:1024), the follower side
(requestVote:1420, appendEntriesAsync:1489 with the inconsistency check
:1661), apply (applyLogToStateMachine:1850 via StateMachineUpdater), and
leader-election wiring.

Structural difference by design: no per-division threads.  Election timeout
detection and commit advancement live in the server-wide QuorumEngine; the
division implements the EngineListener callbacks.  Only transient activities
(an in-flight election, per-follower appenders while leader, the apply loop)
are asyncio tasks.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Optional

import numpy as np

from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.engine.state import (ROLE_CANDIDATE, ROLE_FOLLOWER,
                                    ROLE_LEADER, ROLE_LISTENER)
from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                           LeaderSteppingDownException,
                                           NotLeaderException, RaftException,
                                           StaleReadException,
                                           StateMachineException,
                                           StreamException)
from ratis_tpu.protocol.group import RaftGroup, RaftGroupMemberId
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import (LogEntry, LogEntryKind,
                                         make_transaction_entry)
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer, RaftPeerRole
from ratis_tpu.protocol.raftrpc import (AppendEntriesReply,
                                        AppendEntriesRequest, AppendResult,
                                        RaftRpcHeader, RequestVoteReply,
                                        RequestVoteRequest)
from ratis_tpu.metrics.hops import hop
from ratis_tpu.ops.upkeep import (CH_CACHE, CH_HEARTBEAT, CH_HIBERNATE,
                                  CH_WINDOW)
from ratis_tpu.protocol.requests import (DEFERRED_REPLY, RaftClientReply,
                                         RaftClientRequest, RequestType,
                                         reply_sink_of)
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, TermIndex
from ratis_tpu.server.config import RaftConfiguration
from ratis_tpu.server.election import LeaderElection
from ratis_tpu.server.leader import FollowerInfo, LeaderContext
from ratis_tpu.server.state import ServerState
from ratis_tpu.server.statemachine import StateMachine, TransactionContext
from ratis_tpu.trace.tracer import (STAGE_APPEND, STAGE_APPLY, STAGE_FANOUT,
                                    STAGE_REPLY, STAGE_REPLICATE, STAGE_TXN,
                                    TRACER)
from ratis_tpu.util import injection

LOG = logging.getLogger(__name__)


class Division:
    def __init__(self, server, group: RaftGroup, state_machine: StateMachine,
                 log=None, storage=None):
        self.server = server
        self.group_id: RaftGroupId = group.group_id
        self.member_id = RaftGroupMemberId(server.peer_id, group.group_id)
        self.storage = storage  # RaftStorageDirectory | None
        metadata_io = None
        if storage is not None:
            from ratis_tpu.server.storage import FileMetadataIO
            metadata_io = FileMetadataIO(storage)
        self.state = ServerState(self.member_id, group, log=log,
                                 metadata_io=metadata_io)
        self.state_machine = state_machine
        state_machine.member_id = self.member_id
        # Per-entry SM notification is only dispatched when the app actually
        # overrides it — a no-op coroutine per applied entry is real cost at
        # thousands of groups (StateMachine.notifyTermIndexUpdated analog).
        self._sm_wants_term_index = (
            type(state_machine).notify_term_index_updated
            is not StateMachine.notify_term_index_updated)

        me = group.get_peer(server.peer_id)
        self.role: RaftPeerRole = (RaftPeerRole.LISTENER
                                   if me is not None and me.is_listener()
                                   else RaftPeerRole.FOLLOWER)
        self.leader_ctx: Optional[LeaderContext] = None
        self.election: Optional[LeaderElection] = None
        self._election_task: Optional[asyncio.Task] = None

        p = server.properties
        self._timeout_min_s = RaftServerConfigKeys.Rpc.timeout_min(p).seconds
        self._timeout_max_s = RaftServerConfigKeys.Rpc.timeout_max(p).seconds
        self.pre_vote_enabled = RaftServerConfigKeys.LeaderElection.pre_vote(p)

        from ratis_tpu.server.read import (AppliedIndexWaiters, LeaseState,
                                           WriteIndexCache)
        from ratis_tpu.server.retrycache import RetryCache
        from ratis_tpu.server.snapshot import SnapshotInstaller, SnapshotSender
        from ratis_tpu.server.watch import WatchRequests
        self.retry_cache = RetryCache(
            RaftServerConfigKeys.RetryCache.expiry_time(p).seconds)
        self.watch_requests = WatchRequests(
            RaftServerConfigKeys.Watch.timeout(p).seconds,
            RaftServerConfigKeys.Watch.element_limit(p))
        self.applied_waiters = AppliedIndexWaiters()
        self.write_index_cache = WriteIndexCache(
            p.get_time_duration(
                RaftServerConfigKeys.Read.READ_AFTER_WRITE_CONSISTENT_TIMEOUT_KEY,
                RaftServerConfigKeys.Read
                .READ_AFTER_WRITE_CONSISTENT_TIMEOUT_DEFAULT).seconds)
        self.read_option = RaftServerConfigKeys.Read.option(p)
        self.read_timeout_s = RaftServerConfigKeys.Read.timeout(p).seconds
        self.lease = LeaseState(
            RaftServerConfigKeys.Read.leader_lease_enabled(p),
            RaftServerConfigKeys.Read.leader_lease_timeout_ratio(p),
            RaftServerConfigKeys.Rpc.timeout_min(p).to_ms())
        from ratis_tpu.server.messagestream import MessageStreamRequests
        self.message_stream_requests = MessageStreamRequests(
            RaftServerConfigKeys.Write.byte_limit(p))
        self.snapshot_installer = SnapshotInstaller(self)
        self.snapshot_sender = SnapshotSender(
            self,
            chunk_size=p.get_size(
                RaftServerConfigKeys.Log.Appender.SNAPSHOT_CHUNK_SIZE_MAX_KEY,
                RaftServerConfigKeys.Log.Appender.SNAPSHOT_CHUNK_SIZE_MAX_DEFAULT),
            install_enabled=RaftServerConfigKeys.Log.Appender
            .install_snapshot_enabled(p))
        self._snapshot_auto = RaftServerConfigKeys.Snapshot.auto_trigger_enabled(p)
        self._snapshot_threshold = \
            RaftServerConfigKeys.Snapshot.auto_trigger_threshold(p)
        self._snapshot_retention = \
            RaftServerConfigKeys.Snapshot.retention_file_num(p)
        self._last_snapshot_index = -1
        self._taking_snapshot = False
        self._confirm_inflight: Optional[asyncio.Task] = None
        self._last_cache_sweep = 0.0

        # engine wiring
        self.engine_slot: int = -1
        self.peer_slots: dict[RaftPeerId, int] = {}
        self.max_peers: int = server.engine.state.max_peers

        # upkeep plane (raft.tpu.upkeep.enabled): this division's slot in
        # its loop shard's packed deadline array (server/upkeep.py).  None
        # = legacy per-group paths, bit-for-bit.
        self._upkeep = None
        self.upkeep_slot: int = -1
        self.upkeep_gen: int = -1

        # apply loop
        self._applied_index = -1
        self._apply_wake = asyncio.Event()
        self._apply_task: Optional[asyncio.Task] = None
        self._running = False
        self._rng = random.Random(hash((str(self.member_id),)) & 0xFFFFFFFF)
        self._last_heard_leader_s = 0.0
        # Pipelined leaders keep several AppendEntries in flight; transports
        # deliver per-link FIFO, and this lock keeps *processing* in arrival
        # order too (the reference gets this from its serial gRPC stream,
        # GrpcServerProtocolService appendEntries stream observer).
        self._append_lock = asyncio.Lock()
        self._slowness_timeout_s = \
            RaftServerConfigKeys.Rpc.slowness_timeout(p).seconds
        # Idle-group quiescence (RaftServerConfigKeys.Hibernate; TiKV's
        # hibernate-regions pattern): leader-side sleep bookkeeping.
        self._hibernate_enabled = RaftServerConfigKeys.Hibernate.enabled(p)
        self._hibernate_after = RaftServerConfigKeys.Hibernate.after_sweeps(p)
        self._hibernate_backstop_s = \
            RaftServerConfigKeys.Hibernate.backstop(p).seconds
        self._hibernating = False
        self._quiet_sweeps = 0
        # leader side: monotonic time of the last slow-tick heartbeat sent
        # while asleep (refreshes follower backstop deadlines)
        self._last_hib_slow_tick = 0.0
        # follower side: the armed election deadline is the hibernate
        # BACKSTOP (long), not a normal timeout — client-contact nudges key
        # off this, and any real leader contact clears it
        self._hibernated_follower = False
        # follower-side wake nudge: first client contact on a disarmed
        # timer only RECORDS the moment (the client's retry to the still-
        # alive leader wakes the group properly); a second contact after a
        # full election timeout of continued leader silence re-arms
        self._wake_nudge_s = 0.0
        # staleness grace after wake: the silence was requested, so the
        # leader must get a full leadership-timeout of resumed heartbeats
        # before checkLeadership may judge it again
        self._wake_grace_until = 0.0
        self._election_timeout_min_s = \
            RaftServerConfigKeys.Rpc.timeout_min(p).seconds
        self._slowness_notified: dict[RaftPeerId, float] = {}
        # Fire-and-forget notification tasks: the loop holds only weak refs,
        # so keep strong ones until completion or GC may drop them unrun.
        self._bg_tasks: set[asyncio.Task] = set()
        self._no_leader_timeout_s = \
            RaftServerConfigKeys.Notification.no_leader_timeout(p).seconds
        self._last_no_leader_notify_s = 0.0
        self._started_at_s = 0.0
        self._last_yield_attempt_s = 0.0
        # per-client ordered-async reorder windows (leader only; see
        # _write_ordered)
        self._client_windows: dict = {}
        # Host-path tracing: log index -> (trace_id, append-done ns) for
        # sampled writes in flight between append and apply; _apply_one
        # pops each to close the replicate span and open the apply span,
        # then parks (trace_id, apply-done ns) in _trace_applied for the
        # write handler to close the reply span when its future resumes.
        self._trace_pending: dict[int, tuple[int, int]] = {}
        self._trace_applied: dict[int, tuple[int, int]] = {}
        # Commit fan-out collapse (raft.tpu.replication.reply-fanout):
        # the apply loop resolves the batch's client waiters through ONE
        # waterline fan-out pass, and sink-carrying requests take the
        # deferred-reply path (reply delivered straight into the
        # transport's per-connection batcher, no per-request wakeup chain)
        self._reply_fanout = bool(getattr(server, "reply_fanout", False))
        # peer -> last known commit index (reference CommitInfoCache,
        # RaftServerImpl commitInfoCache): fed by our own commit advances,
        # follower reply piggybacks (leader) and leader request piggybacks
        # (follower); surfaced on every client reply.
        self._commit_info: dict[RaftPeerId, int] = {}
        # memoized (own_commit, infos, wire_form); None = stale
        self._ci_cache = None

        # admin state
        self.pending_reconf = None  # Optional[admin.PendingReconf]
        self.stepping_down = False  # transfer-leadership in progress
        self._election_paused = False

        # metrics (reference RaftServerMetricsImpl / LeaderElectionMetrics /
        # StateMachineMetrics; catalog in ratis-docs metrics.md)
        from ratis_tpu.metrics import (LeaderElectionMetrics,
                                       RaftServerMetrics, StateMachineMetrics)
        self.metrics = RaftServerMetrics(self.member_id)
        self.election_metrics = LeaderElectionMetrics(self.member_id)
        self.sm_metrics = StateMachineMetrics(self.member_id)
        self.sm_metrics.add_applied_index_gauge(lambda: self._applied_index)
        self.metrics.add_commit_info_gauge(
            lambda: {"commitIndex": self.state.log.get_last_committed_index(),
                     "appliedIndex": self._applied_index})
        self.metrics.add_queue_gauge(
            lambda: len(self.leader_ctx.pending) if self.leader_ctx else 0)

    # ------------------------------------------------------------------ util

    def is_leader(self) -> bool:
        return self.role == RaftPeerRole.LEADER

    def is_follower(self) -> bool:
        return self.role == RaftPeerRole.FOLLOWER

    def is_candidate(self) -> bool:
        return self.role == RaftPeerRole.CANDIDATE

    def is_listener(self) -> bool:
        return self.role == RaftPeerRole.LISTENER

    @property
    def applied_index(self) -> int:
        return self._applied_index

    def set_applied_index(self, index: int) -> None:
        """Jump the applied frontier (snapshot install/restore)."""
        self._applied_index = max(self._applied_index, index)
        self.applied_waiters.advance(self._applied_index)
        self._engine_set_applied()

    def random_election_timeout_s(self) -> float:
        return self._rng.uniform(self._timeout_min_s, self._timeout_max_s)

    def get_leader_peer(self) -> Optional[RaftPeer]:
        # NB: a non-leader's hint can never name SELF — abdication without
        # a successor clears leader_id in change_to_follower (a stale
        # self-suggestion pins retrying clients in a self-referral loop).
        lid = self.state.leader_id
        if lid is None:
            return None
        return self.state.configuration.get_peer(lid)

    def introspect(self) -> dict:
        """Structured per-division introspection (the ``/divisions``
        endpoint and the stall watchdog both read this): role, term,
        commit/applied frontier, per-follower replication lag, cache and
        queue sizes, and loop-shard placement.  Pure reads over state the
        division already maintains — safe from the endpoint's connection
        handler on any loop, never awaits."""
        log = self.state.log
        commit = int(log.get_last_committed_index())
        out = {
            "group": str(self.group_id),
            "role": self.role.name,
            "term": int(self.state.current_term),
            "leader": (str(self.state.leader_id)
                       if self.state.leader_id is not None else None),
            "commitIndex": commit,
            "lastApplied": int(self._applied_index),
            "flushIndex": int(log.flush_index),
            "retryCacheSize": len(self.retry_cache),
            "pendingRequests": (len(self.leader_ctx.pending)
                                if self.leader_ctx is not None else 0),
            "hibernating": bool(self._hibernating),
            "loopShard": self.server.shard_of_group(self.group_id),
            "meshSlice": self.server.slice_of_group(self.group_id),
            "shardQueueDepth":
                self.server.shard_queue_depth(self.group_id),
        }
        if self.leader_ctx is not None:
            now = time.monotonic()
            out["followers"] = {
                str(pid): {
                    "matchIndex": int(f.match_index),
                    "nextIndex": int(f.next_index),
                    "lag": max(0, commit - int(f.match_index)),
                    "lastRpcElapsedS": round(
                        now - f.last_rpc_response_s, 3),
                }
                for pid, f in list(self.leader_ctx.followers.items())}
        return out

    # -------------------------------------------------------- engine wiring

    def attach_engine(self) -> None:
        engine = self.server.engine
        # slice-aware slot pin: the group's rows land inside the mesh
        # slice its crc32 hash owns, so its packed events route to the
        # device that holds them (no-op without a mesh: one slice)
        self.engine_slot = engine.attach(
            self, engine.slice_of(self.group_id.to_bytes()))
        self._assign_peer_slots()
        self._sync_conf_to_engine()
        self._engine_set_applied()
        engine.state.role[self.engine_slot] = (
            ROLE_LISTENER if self.is_listener() else ROLE_FOLLOWER)
        if not self.is_listener():
            self.reset_election_deadline()

    def detach_engine(self) -> None:
        if self.engine_slot >= 0:
            self.server.engine.detach(self.engine_slot)
            self.engine_slot = -1

    def _assign_peer_slots(self) -> None:
        """Stable peer->column mapping for the [G, P] arrays.  Existing
        assignments survive conf changes; new peers take free columns;
        columns of long-gone peers are recycled under membership churn."""
        def _take_free() -> int:
            used = set(self.peer_slots.values())
            for i in range(self.max_peers):
                if i not in used:
                    return i
            self._free_stale_slots()
            used = set(self.peer_slots.values())
            for i in range(self.max_peers):
                if i not in used:
                    return i
            raise RaftException(
                f"{self.member_id}: peer-slot columns exhausted "
                f"({self.max_peers}); raise raft.tpu.engine.max-peers")

        for peer in sorted(self.state.configuration.all_peers(),
                           key=lambda p: p.id.id):
            if peer.id not in self.peer_slots:
                self.peer_slots[peer.id] = _take_free()
        if self.member_id.peer_id not in self.peer_slots:
            self.peer_slots[self.member_id.peer_id] = _take_free()

    def _free_stale_slots(self) -> None:
        """Recycle columns of peers in neither conf nor the follower set."""
        keep = {p.id for p in self.state.configuration.all_peers()}
        keep.add(self.member_id.peer_id)
        if self.leader_ctx is not None:
            keep |= set(self.leader_ctx.followers)
        st = self.server.engine.state
        for pid in list(self.peer_slots):
            if pid not in keep:
                col = self.peer_slots.pop(pid)
                if self.engine_slot >= 0:
                    st.match_index[self.engine_slot, col] = -1
                    st.last_ack_ms[self.engine_slot, col] = 0
                    st.priority[self.engine_slot, col] = 0
                    st.peer_index[self.engine_slot, col] = -1
                    st.mark_dirty(self.engine_slot)

    def _sync_conf_to_engine(self) -> None:
        import numpy as np
        conf = self.state.configuration
        self.server.learn_peer_addresses(conf.all_peers())
        n = self.max_peers
        cur = np.zeros(n, bool)
        old = np.zeros(n, bool)
        prio = np.zeros(n, np.int32)
        for p in conf.conf.peers:
            s = self.peer_slots.get(p.id)
            if s is not None:
                cur[s] = True
                prio[s] = p.priority
        if conf.old_conf is not None:
            for p in conf.old_conf.peers:
                s = self.peer_slots.get(p.id)
                if s is not None:
                    old[s] = True
                    prio[s] = p.priority
        me = self.peer_slots[self.member_id.peer_id]
        my_peer = conf.get_peer(self.member_id.peer_id)
        engine = self.server.engine
        # dense peer ids for the lag ledger's per-peer aggregation
        pidx = np.full(n, -1, np.int32)
        for pid, s in self.peer_slots.items():
            pidx[s] = engine.ledger.peer_for(pid)
        engine.state.peer_index[self.engine_slot] = pidx
        engine.state.set_conf(
            self.engine_slot, me, cur, old, prio,
            my_peer.priority if my_peer is not None else 0)

    def _engine_set_applied(self) -> None:
        """Mirror the applied frontier into the lag ledger's [G] array
        (batch-level: once per apply sweep, not per entry)."""
        if self.engine_slot >= 0:
            self.server.engine.state.applied_index[self.engine_slot] = \
                self._applied_index

    def _engine_set_pending(self, n: int) -> None:
        """Mirror the leader pending-queue depth for the ledger/sampler
        (called by PendingRequests on add/pop/drain)."""
        if self.engine_slot >= 0:
            self.server.engine.state.pending_count[self.engine_slot] = n

    def reset_election_deadline(self) -> None:
        self._wake_nudge_s = 0.0
        self._hibernated_follower = False
        if self.engine_slot < 0 or self.is_listener():
            return
        engine = self.server.engine
        deadline = engine.clock.now_ms() + int(self.random_election_timeout_s() * 1000)
        # high-rate path (every append/heartbeat received re-arms): packed
        # update, not a dirty-row refresh
        engine.on_deadline(self.engine_slot, deadline)

    def _engine_set_role(self, role_code: int) -> None:
        if self.engine_slot >= 0:
            self.server.engine.state.role[self.engine_slot] = role_code
            self.server.engine.state.mark_dirty(self.engine_slot)

    def _engine_update_flush(self, sink: Optional[list] = None) -> None:
        if self.engine_slot >= 0:
            if sink is not None:
                # envelope sweep intake: the caller feeds the whole
                # frame's rows to QuorumEngine.on_flush_batch at once
                sink.append((self.engine_slot, self.state.log.flush_index))
                return
            # high-rate path (every append flushes): packed update
            self.server.engine.on_flush(self.engine_slot,
                                        self.state.log.flush_index)

    # ---------------------------------------------------------- lifecycle

    # ------------------------------------------------- live reconfiguration

    def _reconfigurable_keys(self) -> list[str]:
        K = RaftServerConfigKeys
        return [K.Rpc.SLOWNESS_TIMEOUT_KEY,
                K.Notification.NO_LEADER_TIMEOUT_KEY,
                K.Snapshot.AUTO_TRIGGER_ENABLED_KEY,
                K.Snapshot.AUTO_TRIGGER_THRESHOLD_KEY,
                K.Snapshot.RETENTION_FILE_NUM_KEY,
                K.Read.TIMEOUT_KEY]

    async def _apply_reconfiguration(self, key: str, value) -> None:
        """Re-read a runtime-tunable knob from properties (the value was
        already stored by ReconfigurationManager)."""
        p = self.server.properties
        K = RaftServerConfigKeys
        if key == K.Rpc.SLOWNESS_TIMEOUT_KEY:
            self._slowness_timeout_s = K.Rpc.slowness_timeout(p).seconds
        elif key == K.Notification.NO_LEADER_TIMEOUT_KEY:
            self._no_leader_timeout_s = \
                K.Notification.no_leader_timeout(p).seconds
        elif key == K.Snapshot.AUTO_TRIGGER_ENABLED_KEY:
            self._snapshot_auto = K.Snapshot.auto_trigger_enabled(p)
        elif key == K.Snapshot.AUTO_TRIGGER_THRESHOLD_KEY:
            self._snapshot_threshold = K.Snapshot.auto_trigger_threshold(p)
        elif key == K.Snapshot.RETENTION_FILE_NUM_KEY:
            self._snapshot_retention = K.Snapshot.retention_file_num(p)
        elif key == K.Read.TIMEOUT_KEY:
            self.read_timeout_s = K.Read.timeout(p).seconds

    async def start(self) -> None:
        self._running = True
        self._started_at_s = asyncio.get_running_loop().time()
        for key in self._reconfigurable_keys():
            self.server.reconfiguration.register(
                key, self._apply_reconfiguration)
        snapshot_index = -1
        if self.storage is not None:
            # RECOVER path (reference ServerState.initialize:134): reload
            # (term, votedFor), init the SM (restores its latest snapshot),
            # then open the segmented log above the snapshot.
            term, voted_for = self.storage.load_metadata()
            self.state.current_term = term
            self.state.voted_for = voted_for
            conf_entry = self.storage.load_conf_entry()
            if conf_entry is not None:
                self.state.apply_log_entry_configuration(conf_entry)
            else:
                # First boot: record the bootstrap conf so a restart with an
                # empty log still knows the group membership.
                boot = self.state.configuration.to_entry(0, -1)
                await asyncio.to_thread(self.storage.persist_conf_entry, boot)
            await self.state_machine.initialize(
                self.server, self.group_id, self.storage.root)
            snap = self.state_machine.get_latest_snapshot()
            if snap is not None:
                snapshot_index = snap.index
                self._applied_index = snap.index
                self._engine_set_applied()
        else:
            await self.state_machine.initialize(self.server, self.group_id, None)
            snap = None
        await self.state.log.open(snapshot_index)
        if snap is not None and self.state.log.get_last_entry_term_index() is None:
            # Snapshot exists but the log was purged/empty: restart the log
            # just above the snapshot (cf. ServerState.java:153 replay start).
            self.state.log.set_snapshot_boundary(snap.term_index)
        # replay durable conf entries into the configuration history
        log = self.state.log
        for i in range(log.start_index, log.next_index):
            e = log.get(i)
            if e is not None and e.is_config():
                self.state.apply_log_entry_configuration(e)
        self.attach_engine()
        if self.server.upkeep:
            # register on the owning shard's plane (this coroutine already
            # runs on the division's pinned loop, same loop as the plane's
            # sweep — single-threaded by construction)
            self._upkeep = self.server.upkeep_plane_for(
                self.server.shard_of_group(self.group_id))
            self.upkeep_slot, self.upkeep_gen = self._upkeep.register(self)
        # Decoupled-flush observers: the worker's fsync completion advances
        # flush_index -> feed the engine's commit kernel; a failed write is a
        # log failure (StateMachine.notifyLogFailed).
        log.set_flush_callbacks(self._on_log_flush, self._on_log_failed)
        self._apply_task = asyncio.create_task(
            self._apply_loop(), name=f"applier-{self.member_id}")

    def _on_log_flush(self, flush_index: int) -> None:
        self._engine_update_flush()

    def _spawn_bg(self, coro) -> None:
        t = asyncio.ensure_future(coro)
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    def _on_log_failed(self, exc: Exception) -> None:
        if not self._running:
            return
        LOG.error("%s log write failed: %s", self.member_id, exc)
        self._spawn_bg(self._handle_log_failure(exc))

    async def _handle_log_failure(self, exc: Exception) -> None:
        """A broken log cannot back leadership: notify the SM and step down
        (reference EventApi.notifyLogFailed, StateMachine.java:214; the
        reference shuts the division down via the log worker's error path)."""
        try:
            await self.state_machine.notify_log_failed(exc, None)
        except Exception:
            LOG.exception("%s notify_log_failed raised", self.member_id)
        if self.is_leader():
            await self.change_to_follower(self.state.current_term, None,
                                          reason=f"log failed: {exc}")

    async def close(self) -> None:
        self._running = False
        if self._upkeep is not None:
            # generation bump: outstanding (slot, gen) handles — and any
            # deadline already armed — can no longer fire into a future
            # tenant of this slot
            self._upkeep.unregister(self.upkeep_slot, self.upkeep_gen)
            self._upkeep = None
        self.server.reconfiguration.unregister_all(
            self._reconfigurable_keys(), self._apply_reconfiguration)
        if self.election is not None:
            self.election.stop()
        if self._election_task is not None:
            self._election_task.cancel()
        self._drain_client_windows(
            RaftException(f"{self.member_id} is closing"))
        for t in list(self._bg_tasks):
            t.cancel()
        self._bg_tasks.clear()
        if self.leader_ctx is not None:
            await self.leader_ctx.stop()
            self.leader_ctx = None
        if self._apply_task is not None:
            self._apply_task.cancel()
            try:
                await self._apply_task
            except asyncio.CancelledError:
                pass
        self.detach_engine()
        try:
            await self.state.log.close()
            await self.state_machine.close()
        finally:
            self.metrics.unregister()
            self.election_metrics.unregister()
            self.sm_metrics.unregister()
            if self.storage is not None:
                self.storage.unlock()

    # -------------------------------------------------- EngineListener API

    async def on_election_timeout(self) -> None:
        if not self._running or not self.is_follower():
            return
        if self._election_paused \
                or self.state.log.failed \
                or not self.state.configuration.contains_voting(
                    self.member_id.peer_id):
            # A dead log cannot back leadership (the reference terminates the
            # server on log failure): never campaign with one.
            self.reset_election_deadline()
            return
        self.election_metrics.timeout_count.inc()
        self._check_extended_no_leader()
        await self.change_to_candidate()

    def _check_extended_no_leader(self) -> None:
        """Reference RaftServerImpl.checkExtendedNoLeader (via
        StateMachine.notifyExtendedNoLeader, StateMachine.java:255): at each
        election timeout, if no leader has been heard for
        Notification.no_leader_timeout, tell the state machine — at most
        once per timeout period."""
        if self._no_leader_timeout_s <= 0:
            return
        now = asyncio.get_running_loop().time()
        base = max(self._last_heard_leader_s, self._started_at_s)
        if now - base < self._no_leader_timeout_s:
            return
        if now - self._last_no_leader_notify_s < self._no_leader_timeout_s:
            return
        self._last_no_leader_notify_s = now
        self._spawn_bg(self.state_machine.notify_extended_no_leader(
            self.role_info()))

    # ------------------------------------------------ idle-group hibernation

    def _quiescent(self) -> bool:
        """Nothing for this leader's group to say: no pending work and every
        voting follower fully synced with nothing in flight."""
        ctx = self.leader_ctx
        if ctx is None or ctx.pending.requests() \
                or self.watch_requests.pending_count() > 0:
            return False
        log = self.state.log
        last = log.next_index - 1
        if log.get_last_committed_index() != last:
            return False
        conf = self.state.configuration
        for f in ctx.followers.values():
            if not conf.contains_voting(f.peer_id):
                continue
            if f.match_index != last or f.snapshot_in_progress:
                return False
        return True

    def hibernate_sweep(self, now: float) -> str:
        """Called by the server heartbeat sweep per interval (leader +
        coalescing only).  Returns:
        - "awake":   heartbeat normally
        - "request": heartbeat with the hibernate flag (ask followers to
                     disarm their election timers)
        - "asleep":  fully hibernated — contribute NO items this sweep
        """
        if not self._hibernate_enabled or not self.is_leader() \
                or self.leader_ctx is None:
            return "awake"
        if self._hibernating:
            # Dead-leader backstop slow tick: one hibernate-flagged
            # heartbeat per backstop/4 refreshes the followers' (long)
            # backstop deadlines; if this leader dies, the refreshes stop
            # and the group becomes electable again within ~backstop.
            if self._hibernate_backstop_s > 0 and \
                    now - self._last_hib_slow_tick \
                    >= self._hibernate_backstop_s / 4:
                self._last_hib_slow_tick = now
                # The slow tick MUST actually send: heartbeat_item's
                # confirmed-contact gate (0.9*hb fresh-reply / 0.45*hb
                # send-cap) would otherwise suppress it whenever backstop
                # < ~4x the heartbeat interval — the tick counted as sent
                # here while followers heard nothing, and their backstop
                # deadlines expired in a perfectly healthy sleeping group
                # (ADVICE r5).  _last_send_s == 0.0 is the explicit
                # force-due marker heartbeat_item honors.
                for a in self.leader_ctx.appenders.values():
                    a._last_send_s = 0.0
                return "request"
            return "asleep"
        if not self._quiescent():
            self._quiet_sweeps = 0
            return "awake"
        self._quiet_sweeps += 1
        if self._quiet_sweeps < self._hibernate_after:
            return "awake"
        ctx = self.leader_ctx
        conf = self.state.configuration
        voting = [a for a in ctx.appenders.values()
                  if conf.contains_voting(a.follower.peer_id)]
        # An empty voting-appender set (all remaining followers are
        # listeners) is trivially acked — parking in "request" forever
        # would hibernate-flag non-voting followers every sweep with no
        # path to "asleep".
        if all(a.hibernate_acked for a in voting):
            self._hibernating = True
            self._last_hib_slow_tick = now
            LOG.info("%s hibernated (idle %d sweeps)", self.member_id,
                     self._quiet_sweeps)
            return "asleep"
        return "request"

    def wake_from_hibernation(self, reason: str = "") -> None:
        """Any contact (client request, admin op, new entry) wakes the
        group: resume heartbeats and refresh the staleness clock so the
        leader is not instantly declared stale for the silence it was
        ASKED to keep."""
        if not self._hibernating and self._quiet_sweeps == 0:
            return
        was_asleep = self._hibernating
        self._hibernating = False
        self._quiet_sweeps = 0
        # NO fabricated acks: last_ack_ms stays honest (a deposed leader
        # must NOT regain a valid lease from its own wake; see
        # _lease_valid) — the grace window alone suppresses the staleness
        # verdict until resumed heartbeats have had a full timeout to
        # produce REAL acks.
        self._wake_grace_until = (
            asyncio.get_running_loop().time()
            + self.server.engine.leadership_timeout_ms / 1000.0)
        if self.leader_ctx is not None:
            import time as _time
            now_s = _time.monotonic()
            for a in self.leader_ctx.appenders.values():
                a.hibernate_acked = False
                a._last_send_s = 0.0  # next sweep heartbeats immediately
                # slowness bookkeeping must not count the requested silence
                a.follower.last_rpc_response_s = now_s
        if was_asleep:
            LOG.info("%s woke from hibernation (%s)", self.member_id,
                     reason)
        # array mode: the wake moved the true heartbeat due-time to NOW
        # (the force-due marker above); the packed slot must hear it or
        # the plane would sleep out the asleep-era backstop deadline
        self.upkeep_touch_heartbeat()

    @property
    def hibernating(self) -> bool:
        """Engine-visible: suppress per-sweep stale dispatch while asleep
        (the staleness output is level-triggered; a sleeping leader's
        frozen acks would otherwise re-fire it every sweep)."""
        return self._hibernating

    # --------------------------------------------------------- upkeep plane

    def upkeep_touch_heartbeat(self) -> None:
        """Arm CH_HEARTBEAT to fire at the very next sweep.  Called from
        every event that moves the true heartbeat due-time earlier —
        leadership start, hibernation wake, appender added — so the packed
        deadline can only ever be conservative-EARLY (the dispatch re-runs
        the real due gate, so early costs one declined call, never a
        behavior change)."""
        u = self._upkeep
        if u is not None:
            u.set_deadline(self.upkeep_slot, self.upkeep_gen,
                           CH_HEARTBEAT, 0.0)
            u.clear(self.upkeep_slot, self.upkeep_gen, CH_HIBERNATE)

    def next_heartbeat_due(self, now: float) -> float:
        """Min over appenders of the confirmed-contact due-time.  An
        appender-less leader (single-peer group) stays on the sweep
        cadence so hibernation quiescence counting still advances.  With
        heartbeat coalescing OFF the legacy sweep calls every appender's
        ``on_heartbeat_sweep`` each interval as the fill-retry waker, so
        the slot stays due every sweep to preserve that cadence."""
        ctx = self.leader_ctx
        if not self.is_leader() or ctx is None:
            return float("inf")
        if not self.server.heartbeat_coalescing or not ctx.appenders:
            return now if not self.server.heartbeat_coalescing \
                else now + self.server.heartbeat_interval_s
        return min(a.next_due(now) for a in ctx.appenders.values())

    def upkeep_rearm_heartbeat(self, now: float) -> None:
        """Post-dispatch re-arm of the leader channels from current state:
        awake leaders arm CH_HEARTBEAT, asleep ones arm the CH_HIBERNATE
        backstop clock instead (the slot is then touched a handful of
        times per minute, not every sweep), non-leaders hold +inf."""
        u = self._upkeep
        if u is None:
            return
        slot, gen = self.upkeep_slot, self.upkeep_gen
        if not self.is_leader() or self.leader_ctx is None:
            u.clear(slot, gen, CH_HEARTBEAT)
            u.clear(slot, gen, CH_HIBERNATE)
        elif self._hibernating:
            u.clear(slot, gen, CH_HEARTBEAT)
            if self._hibernate_backstop_s > 0:
                u.set_deadline(slot, gen, CH_HIBERNATE,
                               self._last_hib_slow_tick
                               + self._hibernate_backstop_s / 4)
            else:
                # backstop 0 = round-4 full disarm: the group costs
                # nothing until contact wakes it
                u.clear(slot, gen, CH_HIBERNATE)
        else:
            u.clear(slot, gen, CH_HIBERNATE)
            u.set_deadline(slot, gen, CH_HEARTBEAT,
                           self.next_heartbeat_due(now))

    def upkeep_arm_cache(self, now: float) -> None:
        """Arm the CH_CACHE expiry waterline when entries exist and the
        channel is unarmed (write/apply paths; O(1) while armed — the
        oldest-entry scan only runs on the empty->non-empty transition)."""
        u = self._upkeep
        if u is None or u.is_armed(self.upkeep_slot, self.upkeep_gen,
                                   CH_CACHE):
            return
        when = min(self.retry_cache.next_expiry_s(),
                   self.write_index_cache.next_expiry_s())
        if when != float("inf"):
            u.set_deadline(self.upkeep_slot, self.upkeep_gen, CH_CACHE, when)

    def sweep_caches(self, now: float) -> float:
        """CH_CACHE dispatch: run both expiry sweeps (identical bodies to
        the legacy apply-loop slow tick) and return the new waterline —
        +inf once both caches drain, so an idle division disarms."""
        self.retry_cache.sweep()
        self.write_index_cache.sweep(now)
        return min(self.retry_cache.next_expiry_s(),
                   self.write_index_cache.next_expiry_s())

    def upkeep_arm_window(self) -> None:
        """Arm CH_WINDOW once the reorder-window census crosses the sweep
        threshold (the legacy per-write sweep is a no-op below it)."""
        u = self._upkeep
        if u is None or len(self._client_windows) <= 256 \
                or u.is_armed(self.upkeep_slot, self.upkeep_gen, CH_WINDOW):
            return
        u.set_deadline(self.upkeep_slot, self.upkeep_gen, CH_WINDOW,
                       asyncio.get_running_loop().time() + 30.0)

    def sweep_client_windows_due(self) -> float:
        """CH_WINDOW dispatch: same expiry policy as the legacy per-write
        ``_sweep_client_windows``; next due-time, +inf when the census is
        back under the threshold (re-armed by the next window creation)."""
        self._sweep_client_windows(force=True)
        if len(self._client_windows) > 256:
            return asyncio.get_running_loop().time() + 30.0
        return float("inf")

    def on_commit_advance_now(self, new_commit: int) -> None:
        """Engine advanced this group's commit (leader only).  Synchronous
        on purpose: the engine calls this INLINE from the ack intake path
        (QuorumEngine.on_ack) so a commit never waits for the tick task to
        win a turn on a loaded event loop; the body must stay await-free."""
        if not self.is_leader():
            return
        self.state.log.update_commit_index(new_commit,
                                           self.state.current_term, True)
        self._apply_wake.set()
        self._update_watch_frontiers()

    async def on_commit_advance(self, new_commit: int) -> None:
        self.on_commit_advance_now(new_commit)

    async def on_leadership_stale(self) -> None:
        if self._hibernating:
            # silence was requested (followers' timers are disarmed too);
            # staleness detection resumes at wake
            return
        if asyncio.get_running_loop().time() < self._wake_grace_until:
            return  # just woke: give resumed heartbeats a full window
        if self.is_leader():
            await self.change_to_follower(
                self.state.current_term, None,
                reason="no majority ack within leadership timeout")

    # ----------------------------------------------------- role transitions

    async def change_to_candidate(self, force: bool = False) -> None:
        assert self.is_follower()
        self.role = RaftPeerRole.CANDIDATE
        self._engine_set_role(ROLE_CANDIDATE)
        self.election = LeaderElection(self, force=force)
        if force:
            # Leadership-transfer target (dissertation §3.10 TimeoutNow):
            # own the higher term IMMEDIATELY — the in-memory bump happens
            # before any await — so the old leader's in-flight heartbeats
            # (still at the old term) are rejected instead of demoting this
            # candidacy before its vote requests ever go out.  The old
            # leader steps down when it sees the higher term in replies.
            await self.state.init_election_term()
            self.election.term_pre_initialized = True

        async def _run_and_rearm():
            try:
                await self.election.run()
            except asyncio.CancelledError:
                raise
            except Exception:
                LOG.exception("%s election failed", self.member_id)
            finally:
                if self.is_candidate():
                    # election did not conclude in leadership: back to follower
                    self.role = RaftPeerRole.FOLLOWER
                    self._engine_set_role(ROLE_FOLLOWER)
                    self.reset_election_deadline()

        self._election_task = asyncio.create_task(
            _run_and_rearm(), name=f"election-{self.member_id}")

    async def bootstrap_as_leader(self) -> None:
        """Deployment-mode APPOINTED-LEADER bootstrap: install leadership
        directly — term 1, self-vote persisted, startup conf entry,
        appenders — with NO vote round.  For fresh groups only; the
        followers adopt the term from the first heartbeat/append exactly as
        they would after a won election.

        Contract (operator-owned, like the reference's startup-role /
        priority machinery that legitimizes operator-chosen initial
        leaders, LeaderElection.java:80, RaftPeer startup roles): appoint
        EXACTLY ONE peer per group, at group creation, before any traffic.
        Two appointees would be two same-term leaders — the vote round
        this skips is what normally forbids that.  Guarded to fresh state
        so it can never fire on a group with history.

        Why it exists: mass bring-up (the 10k-group multi-raft shape) pays
        O(groups x peers) vote RPCs and election machinery for an outcome
        the deployment already chose; measured at 5-peer x 10240 groups
        this was the dominant bring-up cost."""
        if not self.is_follower() or self.state.current_term != 0 \
                or self.state.leader_id is not None \
                or self.state.log.get_last_entry_term_index() is not None:
            raise RaftException(
                f"{self.member_id}: appointed bootstrap requires a fresh "
                f"group (follower at term 0 with an empty log)")
        if not self.state.configuration.contains_voting(
                self.member_id.peer_id):
            raise RaftException(
                f"{self.member_id}: appointed bootstrap of a non-voting "
                f"member")
        # Deterministic appointee: the fresh-state guard above is peer-
        # LOCAL, so without this check two appointees on the same fresh
        # group would both pass it and become two term-1 leaders whose
        # conflicting index-1 entries can each gather acks (ADVICE r5).
        # Deriving the one legitimate appointee from the configuration
        # itself (highest priority, ties broken by lowest peer id) makes a
        # double appointment fail CLOSED on every peer but one, with no
        # coordination or persisted marker needed.
        appointee = self.bootstrap_appointee()
        if appointee != self.member_id.peer_id:
            raise RaftException(
                f"{self.member_id}: not the bootstrap appointee — this "
                f"configuration appoints {appointee} (highest priority, "
                f"lowest peer id); appointing anyone else risks two "
                f"term-1 leaders on the same group")
        await self.state.init_election_term()
        self.role = RaftPeerRole.CANDIDATE
        self._engine_set_role(ROLE_CANDIDATE)
        await self.change_to_leader()

    def bootstrap_appointee(self) -> RaftPeerId:
        """The one peer this configuration allows to bootstrap_as_leader:
        the voting peer with the highest priority, ties broken by lowest
        peer id — deterministic from the conf every peer shares."""
        voting = self.state.configuration.voting_peers()
        if not voting:
            raise RaftException(
                f"{self.member_id}: configuration has no voting peers")
        return min(voting, key=lambda p: (-p.priority, p.id.id)).id

    async def change_to_leader(self) -> None:
        assert self.is_candidate()
        self.role = RaftPeerRole.LEADER
        self.election_metrics.on_new_leader_elected()
        self.state.set_leader(self.member_id.peer_id)
        self._engine_set_role(ROLE_LEADER)
        st = self.server.engine.state
        st.election_deadline_ms[self.engine_slot] = np.iinfo(np.int32).max
        now = self.server.engine.clock.now_ms()
        st.last_ack_ms[self.engine_slot, :] = now
        st.match_index[self.engine_slot, :] = -1
        st.mark_dirty(self.engine_slot)

        self.watch_requests.reset_frontiers()
        self.leader_ctx = LeaderContext(self)
        # Append the startup placeholder entry carrying the current conf
        # (reference appends a conf/StartupLogEntry on election,
        # LeaderStateImpl.java:293): commits of earlier-term entries are
        # gated on this index (Raft §5.4.2).
        conf = self.state.configuration
        index = self.state.log.next_index
        entry = conf.to_entry(self.state.current_term, index)
        ctx = self.leader_ctx
        ctx.startup_index = index
        st.first_leader_index[self.engine_slot] = index
        st.mark_dirty(self.engine_slot)
        try:
            await self.state.log.append_entry(entry)
        except Exception as e:
            # Log died between the vote and the startup append: abdicate
            # immediately instead of lingering as a heartbeat-less leader.
            LOG.error("%s startup entry append failed: %s", self.member_id, e)
            await self.change_to_follower(self.state.current_term, None,
                                          reason=f"startup append failed: {e}")
            return
        if self.leader_ctx is not ctx or not self.is_leader():
            # Deposed DURING the startup append (a higher-term append or
            # vote landed in the await window and change_to_follower
            # already unwound leader_ctx — an election-storm interleaving
            # the chaos campaign hits at the 1024-group shape): the new
            # role owns the division now; starting appenders for the dead
            # context would crash (or leak a ghost leadership).
            LOG.info("%s deposed during startup append; staying %s",
                     self.member_id, self.role.name)
            return
        self.state.apply_log_entry_configuration(entry)
        self._engine_update_flush()
        self.leader_ctx.start_appenders()
        # array mode: fresh leadership is due immediately (covers the
        # appender-less single-peer case start_appenders' per-appender
        # touch cannot)
        self.upkeep_touch_heartbeat()
        LOG.info("%s became LEADER at term %d", self.member_id,
                 self.state.current_term)

    async def change_to_follower(self, term: int, leader_id: Optional[RaftPeerId],
                                 reason: str = "") -> None:
        old_role = self.role
        if self.is_listener():
            await self.state.update_current_term(term)
            if leader_id is not None:
                self.state.set_leader(leader_id)
            return
        self.role = RaftPeerRole.FOLLOWER
        self._engine_set_role(ROLE_FOLLOWER)
        await self.state.update_current_term(term)
        if leader_id is not None:
            changed = self.state.set_leader(leader_id)
            if changed:
                await self.state_machine.notify_leader_changed(
                    self.member_id, leader_id)
        self._hibernating = False
        self._quiet_sweeps = 0
        if self._upkeep is not None:
            # non-leaders hold +inf on the leader channels — this is where
            # the vectorized sweep's savings come from
            self._upkeep.clear(self.upkeep_slot, self.upkeep_gen,
                               CH_HEARTBEAT)
            self._upkeep.clear(self.upkeep_slot, self.upkeep_gen,
                               CH_HIBERNATE)
        if old_role == RaftPeerRole.LEADER and leader_id is None:
            # Abdication without a known successor: the stale hint still
            # names SELF, and every leader_id consumer (NotLeader
            # suggestions, readIndex forwarding, GroupInfo) would keep
            # reporting this non-leader as the leader — clients retrying
            # the suggestion would loop on this node forever.  We genuinely
            # don't know the leader: clear it.
            self.state.set_leader(None)
        if old_role == RaftPeerRole.LEADER and self.leader_ctx is not None:
            self.message_stream_requests.clear()
            self._trace_pending.clear()  # entries may truncate; never apply
            self._trace_applied.clear()
            ctx = self.leader_ctx
            self.leader_ctx = None
            nle = NotLeaderException(self.member_id, self.get_leader_peer(),
                                     self.state.configuration.all_peers())
            await ctx.stop(nle)
            self.watch_requests.drain(nle)
            self._drain_client_windows(nle)
            LOG.info("%s stepped down (%s)", self.member_id, reason)
        if old_role == RaftPeerRole.CANDIDATE and self.election is not None:
            self.election.stop()
        if self.pending_reconf is not None \
                and not self.pending_reconf.future.done():
            self.pending_reconf.future.set_exception(
                NotLeaderException(self.member_id, self.get_leader_peer(),
                                   self.state.configuration.all_peers()))
        self.reset_election_deadline()

    # ------------------------------------------------------- follower RPCs

    async def handle_request_vote(self, req: RequestVoteRequest) -> RequestVoteReply:
        await injection.execute(injection.REQUEST_VOTE, self.member_id,
                                req.header.requestor_id)
        state = self.state
        header = RaftRpcHeader(self.member_id.peer_id, req.header.requestor_id,
                               self.group_id)
        my_last = state.log.get_last_entry_term_index() or TermIndex.INITIAL_VALUE

        def reply(granted: bool, term: int) -> RequestVoteReply:
            return RequestVoteReply(header, term, granted, last_entry=my_last)

        candidate = req.header.requestor_id
        # Listener never votes (quorum exclusion).
        if self.is_listener():
            return reply(False, state.current_term)

        if req.candidate_term < state.current_term:
            return reply(False, state.current_term)

        # Leader stickiness: deny if we recently heard from a live leader
        # (reference VoteContext lease check) — applies to both phases.
        loop_now = asyncio.get_running_loop().time()
        has_live_leader = (state.leader_id is not None
                           and state.leader_id != candidate
                           and (loop_now - self._last_heard_leader_s)
                           < self._timeout_min_s)
        if has_live_leader and not req.force:
            return reply(False, state.current_term)

        if req.pre_vote:
            # no term/vote changes; just report whether we WOULD vote
            ok = state.is_log_up_to_date(req.candidate_last_entry)
            return reply(ok, state.current_term)

        if req.candidate_term > state.current_term:
            await self.change_to_follower(req.candidate_term, None,
                                          reason="higher term in vote request")

        granted = False
        if (state.voted_for is None or state.voted_for == candidate) \
                and state.is_log_up_to_date(req.candidate_last_entry):
            await state.grant_vote(candidate)
            self.reset_election_deadline()
            granted = True
        return reply(granted, state.current_term)

    def append_lock_locked(self) -> bool:
        """Whether an append/bulk-heartbeat is currently holding this
        division's serialization lock (used by the server's bulk-heartbeat
        receiver to defer contended items off its sequential sweep)."""
        return self._append_lock.locked()

    async def handle_append_entries(self, req: AppendEntriesRequest,
                                    flush_sink: Optional[list] = None
                                    ) -> AppendEntriesReply:
        """``flush_sink`` (envelope sweep intake): collect this append's
        engine flush update as a packed ``(slot, flush_index)`` row instead
        of a scalar ``on_flush`` call — the server feeds the whole frame's
        rows to ``QuorumEngine.on_flush_batch`` in one pass."""
        with self.metrics.follower_append_timer.time():
            async with self._append_lock:
                return await self._handle_append_entries_impl(req,
                                                              flush_sink)

    async def _handle_append_entries_impl(self, req: AppendEntriesRequest,
                                          flush_sink: Optional[list] = None
                                          ) -> AppendEntriesReply:
        await injection.execute(injection.APPEND_ENTRIES, self.member_id,
                                req.header.requestor_id)
        state = self.state
        log = state.log
        header = RaftRpcHeader(self.member_id.peer_id, req.header.requestor_id,
                               self.group_id)

        def reply(result: AppendResult, next_index: int) -> AppendEntriesReply:
            return AppendEntriesReply(
                header, state.current_term, result, next_index,
                log.get_last_committed_index(), log.flush_index,
                is_heartbeat=req.is_heartbeat())

        if req.leader_term < state.current_term:
            return reply(AppendResult.NOT_LEADER, log.next_index)

        # Recognize the leader: higher-or-equal term append wins.
        if req.leader_term > state.current_term or not self.is_follower() \
                or state.leader_id != req.header.requestor_id:
            await self.change_to_follower(req.leader_term,
                                          req.header.requestor_id,
                                          reason="append from leader")
        self._last_heard_leader_s = asyncio.get_running_loop().time()
        self.reset_election_deadline()
        for pid, idx in req.commit_infos:
            self.update_commit_info(RaftPeerId.value_of(pid), idx)

        # Inconsistency check (checkInconsistentAppendEntries:1661).
        if req.previous is not None:
            ti = log.get_term_index(req.previous.index)
            if ti is None and self._snapshot_matches(req.previous):
                ti = req.previous
            if ti is None or ti.term != req.previous.term:
                hint = min(log.next_index, req.previous.index)
                return reply(AppendResult.INCONSISTENCY, max(hint, log.start_index))

        if req.entries:
            old_next = log.next_index
            await log.append_entries_follower(req.entries)
            if log.next_index < old_next:
                state.truncate_configurations(log.next_index)
            for e in req.entries:
                if e.is_config():
                    state.apply_log_entry_configuration(e)
                    self.on_configuration_changed()
            self._engine_update_flush(flush_sink)

        # Follower commit: only up to the frontier THIS request verified
        # against the leader's log (Raft §5.3: min(leaderCommit, index of
        # last new entry); the prev check transitively verifies everything
        # at or below prev).  Capping at flush_index alone is unsafe: it can
        # cover a stale uncommitted tail from an old term that a heartbeat
        # never examined — committing it would commit an entry the current
        # leader is about to truncate away (found by the chaos suite as a
        # follower wedged on 'conflict at committed index').
        covered = (req.entries[-1].index if req.entries
                   else (req.previous.index if req.previous is not None
                         else -1))
        commit = min(req.leader_commit, covered, log.flush_index)
        if log.update_commit_index(commit, state.current_term, False):
            self._apply_wake.set()

        return reply(AppendResult.SUCCESS, log.next_index)

    async def on_bulk_heartbeat(self, leader_id: RaftPeerId, term: int,
                                leader_commit: int, commit_term: int,
                                hibernate: bool = False
                                ) -> tuple[int, int, int, int, int]:
        """One compact heartbeat item (protocol.raftrpc.BulkHeartbeat): the
        idle happy path of handle_append_entries without request building —
        leadership recognition, election-deadline reset, and commit advance
        gated on the Log Matching property (our entry at leader_commit must
        carry commit_term; identical (term, index) implies an identical
        prefix, so committing up to it is exactly as safe as the prev-check
        path).  Anything this cannot verify is left to the full
        AppendEntries probe the leader falls back to.

        Runs under the same _append_lock that serializes
        handle_append_entries: append_entries_follower awaits mid-scan
        (truncate/flush), and a heartbeat from a new-term leader landing in
        that window could change_to_follower and advance the commit index
        over entries the resumed (now stale-leader) append then truncates —
        destroying committed state.  The lock is uncontended on the idle
        happy path this fast-path serves."""
        async with self._append_lock:
            return await self._on_bulk_heartbeat_locked(
                leader_id, term, leader_commit, commit_term, hibernate)

    async def _on_bulk_heartbeat_locked(self, leader_id: RaftPeerId,
                                        term: int, leader_commit: int,
                                        commit_term: int,
                                        hibernate: bool = False
                                        ) -> tuple[int, int, int, int, int]:
        from ratis_tpu.protocol.raftrpc import (BULK_HB_HIBERNATED,
                                                BULK_HB_NOT_LEADER,
                                                BULK_HB_OK)
        state = self.state
        log = state.log
        if term < state.current_term:
            return (BULK_HB_NOT_LEADER, state.current_term, log.next_index,
                    log.get_last_committed_index(), log.flush_index)
        if term > state.current_term or not self.is_follower() \
                or state.leader_id != leader_id:
            await self.change_to_follower(term, leader_id,
                                          reason="bulk heartbeat from leader")
        self._last_heard_leader_s = asyncio.get_running_loop().time()
        self.reset_election_deadline()
        if commit_term > 0 and leader_commit > log.get_last_committed_index():
            ti = log.get_term_index(leader_commit)
            if ti is not None and ti.term == commit_term:
                commit = min(leader_commit, log.flush_index)
                if log.update_commit_index(commit, state.current_term, False):
                    self._apply_wake.set()
        if hibernate:
            # Idle-group quiescence: the leader asks to stop heartbeating.
            # Accept only when fully synced with the leader's commit
            # frontier — the item carries real commit info, so a lagging
            # follower catches up right here and accepts on a later sweep;
            # otherwise the armed timer makes the leader keep heartbeating.
            # Accepting arms the long BACKSTOP deadline (not a full disarm):
            # the sleeping leader's slow tick keeps refreshing it, so a dead
            # leader is detected within ~backstop even with zero client
            # traffic (backstop=0 restores the full disarm).
            if log.get_last_committed_index() >= leader_commit \
                    and log.flush_index >= leader_commit \
                    and self.engine_slot >= 0:
                from ratis_tpu.engine.state import NO_DEADLINE
                if self._hibernate_backstop_s > 0:
                    # clamp: the engine's deadline array is int32 ms, and a
                    # "30d" backstop must degrade to the sentinel (full
                    # disarm), not overflow the store
                    deadline = min(
                        self.server.engine.clock.now_ms() + int(
                            (self._hibernate_backstop_s
                             + self.random_election_timeout_s()) * 1000),
                        NO_DEADLINE)
                else:
                    deadline = NO_DEADLINE
                self.server.engine.on_deadline(self.engine_slot, deadline)
                self._hibernated_follower = True
                return (BULK_HB_HIBERNATED, state.current_term,
                        log.next_index, log.get_last_committed_index(),
                        log.flush_index)
        return (BULK_HB_OK, state.current_term, log.next_index,
                log.get_last_committed_index(), log.flush_index)

    async def handle_install_snapshot(self, req):
        """Follower side of snapshot install: chunked file mode or
        notification mode (SnapshotInstallationHandler.java:60)."""
        from ratis_tpu.protocol.raftrpc import (InstallSnapshotReply,
                                                InstallSnapshotResult)
        await injection.execute(injection.INSTALL_SNAPSHOT, self.member_id,
                                req.header.requestor_id)
        header = RaftRpcHeader(self.member_id.peer_id, req.header.requestor_id,
                               self.group_id)
        state = self.state

        def reply(result, snapshot_index: int = -1):
            return InstallSnapshotReply(header, state.current_term, result,
                                        req.request_index, snapshot_index)

        if req.leader_term < state.current_term:
            return reply(InstallSnapshotResult.NOT_LEADER)
        if req.leader_term > state.current_term or not self.is_follower():
            await self.change_to_follower(req.leader_term,
                                          req.header.requestor_id,
                                          reason="install snapshot from leader")
        self._last_heard_leader_s = asyncio.get_running_loop().time()
        self.reset_election_deadline()

        if req.is_notification():
            # App-managed state transfer (StateMachine.java:293).
            installed = await self.state_machine \
                .notify_install_snapshot_from_leader(
                    None, req.notification_first_available)
            if installed is not None:
                self.state.log.set_snapshot_boundary(installed)
                self.set_applied_index(installed.index)
                return reply(InstallSnapshotResult.SNAPSHOT_INSTALLED,
                             installed.index)
            snap = self.state_machine.get_latest_snapshot()
            if snap is not None and req.notification_first_available is not None \
                    and snap.index + 1 >= req.notification_first_available.index:
                return reply(InstallSnapshotResult.ALREADY_INSTALLED, snap.index)
            return reply(InstallSnapshotResult.IN_PROGRESS)

        try:
            result = await self.snapshot_installer.receive(req)
        except RaftException as e:
            LOG.warning("%s snapshot install failed: %s", self.member_id, e)
            return reply(InstallSnapshotResult.SNAPSHOT_UNAVAILABLE)
        idx = (req.snapshot_term_index.index
               if req.snapshot_term_index is not None else -1)
        return reply(result, idx if result == InstallSnapshotResult.SUCCESS else -1)

    async def handle_read_index(self, req):
        """Leader side of follower-served linearizable reads: confirm
        leadership, return commitIndex (readIndexAsync in the reference)."""
        from ratis_tpu.protocol.raftrpc import ReadIndexReply
        header = RaftRpcHeader(self.member_id.peer_id, req.header.requestor_id,
                               self.group_id)
        if not self.is_leader() or self.leader_ctx is None \
                or self._applied_index < self.leader_ctx.startup_index:
            return ReadIndexReply(header, False)  # not (ready as) leader
        try:
            read_index = await self._leader_read_index()
        except RaftException:
            return ReadIndexReply(header, False)
        return ReadIndexReply(header, True, read_index)

    async def handle_start_leader_election(self, req):
        """Transfer-leadership target: start an immediate (forced) election
        (reference RaftServerImpl.startLeaderElection:1735)."""
        from ratis_tpu.protocol.raftrpc import StartLeaderElectionReply
        header = RaftRpcHeader(self.member_id.peer_id, req.header.requestor_id,
                               self.group_id)
        my_last = self.state.log.get_last_entry_term_index() \
            or TermIndex.INITIAL_VALUE
        if not self.is_follower() or my_last < req.leader_last_entry:
            return StartLeaderElectionReply(header, False)
        await self.change_to_candidate(force=True)
        return StartLeaderElectionReply(header, True)

    def _snapshot_matches(self, ti: TermIndex) -> bool:
        snap = self.state_machine.get_latest_snapshot()
        return snap is not None and snap.term_index == ti

    def snapshot_covers(self, index: int) -> bool:
        snap = self.state_machine.get_latest_snapshot()
        return snap is not None and snap.index >= index

    def snapshot_term_index(self, index: int) -> Optional[TermIndex]:
        snap = self.state_machine.get_latest_snapshot()
        if snap is not None and snap.index == index:
            return snap.term_index
        return None

    async def try_install_snapshot(self, follower: FollowerInfo) -> bool:
        """Follower is behind the purged log: ship the snapshot
        (GrpcLogAppender.installSnapshot:764 / notify:805 decision)."""
        if follower.snapshot_in_progress:
            return False
        follower.snapshot_in_progress = True
        try:
            return await self.snapshot_sender.send_to(follower)
        except Exception:
            LOG.exception("%s snapshot install to %s failed", self.member_id,
                          follower.peer_id)
            return False
        finally:
            follower.snapshot_in_progress = False

    # ------------------------------------------------------------ snapshots

    async def take_snapshot_async(self) -> int:
        """Take a snapshot now and purge the covered log
        (StateMachineUpdater.takeSnapshot:286 + purge:80); also serves the
        client-triggered path (SnapshotManagementRequestHandler)."""
        if self._taking_snapshot:
            return self._last_snapshot_index
        self._taking_snapshot = True
        try:
            with self.sm_metrics.snapshot_timer.time():
                index = await self.state_machine.take_snapshot()
            if index < 0:
                return index
            self._last_snapshot_index = index
            if self._snapshot_retention > 0:
                self.state_machine.get_state_machine_storage() \
                    .clean_old_snapshots(self._snapshot_retention)
            await self.state.log.purge(index)
            return index
        finally:
            self._taking_snapshot = False

    def _should_auto_snapshot(self) -> bool:
        return (self._snapshot_auto
                and self._applied_index - max(self._last_snapshot_index, 0)
                >= self._snapshot_threshold)

    # ------------------------------------------------------- watch frontiers

    def _update_watch_frontiers(self, force: bool = False) -> None:
        """Recompute the four replication-level frontiers
        (LeaderStateImpl.commitIndexChanged:579 + watchRequests.update:986)."""
        if not self.is_leader() or self.leader_ctx is None:
            return
        if not force and self.watch_requests.pending_count() == 0:
            return  # runs on every follower ack; skip the math when idle
        log = self.state.log
        commit = log.get_last_committed_index()
        match_all = [log.flush_index]
        commit_all = [commit]
        commit_voting = [commit]
        conf = self.state.configuration
        for f in self.leader_ctx.followers.values():
            match_all.append(f.match_index)
            commit_all.append(f.commit_index)
            if conf.contains_voting(f.peer_id):
                commit_voting.append(f.commit_index)
        majority_committed = sorted(commit_voting)[(len(commit_voting) - 1) // 2]
        self.watch_requests.update_all_levels(
            majority_commit=commit,
            all_match=min(match_all),
            majority_committed=majority_committed,
            all_committed=min(commit_all))

    # --------------------------------------------------------- leader acks

    def on_follower_ack(self, follower: FollowerInfo,
                        ack_sink: Optional[list] = None) -> None:
        slot = self.peer_slots.get(follower.peer_id)
        if slot is not None and self.engine_slot >= 0:
            if ack_sink is not None:
                # packed intake (sweep mode): the caller feeds the whole
                # reply frame's rows to QuorumEngine.on_ack_batch at once
                ack_sink.append((self.engine_slot, slot,
                                 follower.match_index))
            else:
                self.server.engine.on_ack(self.engine_slot, slot,
                                          follower.match_index)
        if self._upkeep is not None:
            # fold per-ack frontier math into one pass at the next sweep
            # (commit-level watches stay prompt via on_commit_advance_now);
            # same idle gate as _update_watch_frontiers — with no pending
            # watch the numpy mark itself is hot-ack-path overhead
            if self.watch_requests.pending_count():
                self._upkeep.mark_watch_dirty(self.upkeep_slot,
                                              self.upkeep_gen)
        else:
            self._update_watch_frontiers()

    def on_follower_match_regressed(self, follower: FollowerInfo) -> None:
        """A follower provably lost acked entries (volatile-log restart):
        write the lowered match through to the engine mirror so quorum math
        no longer counts the lost entries."""
        slot = self.peer_slots.get(follower.peer_id)
        if slot is not None and self.engine_slot >= 0:
            self.server.engine.regress_match(self.engine_slot, slot,
                                             follower.match_index)

    def check_yield_to_higher_priority(self) -> None:
        """Auto-yield (reference LeaderStateImpl.checkPeersForYieldingLeader
        :1058, run at the checkLeadership cadence): a leader whose current
        conf contains a strictly higher-priority, fully caught-up voting
        peer fires a forced election on it — how setConfiguration priority
        changes move leadership without an explicit transfer."""
        if not self.is_leader() or self.leader_ctx is None \
                or self.stepping_down or self.pending_reconf is not None:
            return
        conf = self.state.configuration
        if conf.is_transitional():
            return
        now = asyncio.get_running_loop().time()
        if now - self._last_yield_attempt_s < self._timeout_min_s:
            return  # give the previous forced election a round to land
        last = self.state.log.next_index - 1
        target = None
        # any caught-up AND LIVE peer above our priority qualifies (highest
        # first) — a crashed top-priority peer must not block yielding to
        # the next one, matching the reference's chooseUpToDateFollower
        # over ALL higher-priority appenders.  Liveness = a reply within
        # one election timeout (an idle log keeps match_index satisfied
        # forever, so match alone can't prove the peer is up).
        live_after = time.monotonic() - self._timeout_max_s
        for p in self.higher_priority_peers():
            f = self.leader_ctx.followers.get(p.id)
            if f is not None and f.match_index >= last \
                    and f.last_rpc_response_s >= live_after:
                target = p
                break
        if target is None:
            return  # none caught up yet; appenders keep catching them up
        self._last_yield_attempt_s = now
        LOG.info("%s yielding leadership to higher-priority %s",
                 self.member_id, target.id)
        self._spawn_bg(self._send_start_leader_election(target.id))

    def higher_priority_peers(self) -> list:
        """Voting peers with priority strictly above ours, highest first
        (shared by auto-yield and the explicit no-target transfer)."""
        conf = self.state.configuration
        me = conf.get_peer(self.member_id.peer_id)
        if me is None:
            return []
        return sorted((p for p in conf.voting_peers()
                       if p.id != me.id and p.priority > me.priority),
                      key=lambda p: -p.priority)

    async def _send_start_leader_election(self, target_id: RaftPeerId) -> None:
        from ratis_tpu.protocol.raftrpc import StartLeaderElectionRequest
        hdr = RaftRpcHeader(self.member_id.peer_id, target_id, self.group_id)
        last_ti = self.state.log.get_last_entry_term_index()
        try:
            await self.server.send_server_rpc(
                target_id, StartLeaderElectionRequest(hdr, last_ti))
        except Exception as e:
            LOG.warning("%s startLeaderElection to %s failed: %s",
                        self.member_id, target_id, e)

    def check_follower_slowness(self, follower: FollowerInfo) -> None:
        """Leader-side slow-follower detection (reference
        RaftServerImpl.checkSlowness via LogAppenderBase + StateMachine
        .notifyFollowerSlowness, StateMachine.java:247): if a follower has
        not responded for Rpc.slowness_timeout, tell the state machine —
        at most once per timeout period per follower."""
        if self._slowness_timeout_s <= 0 or follower.snapshot_in_progress:
            # A follower taking a (possibly long) snapshot install is busy,
            # not slow; its chunk replies refresh last_rpc_response_s anyway.
            return
        now = time.monotonic()
        elapsed = now - follower.last_rpc_response_s
        if elapsed < self._slowness_timeout_s:
            self._slowness_notified.pop(follower.peer_id, None)
            return
        last = self._slowness_notified.get(follower.peer_id, 0.0)
        if now - last < self._slowness_timeout_s:
            return
        self._slowness_notified[follower.peer_id] = now
        peer = self.state.configuration.get_peer(follower.peer_id)
        self._spawn_bg(self.state_machine.notify_follower_slowness(
            self.role_info(), peer))

    def role_info(self):
        """A RoleInfoProto analog handed to StateMachine notifications
        (reference RoleInfoProto, Raft.proto:537)."""
        return {
            "peer_id": str(self.member_id.peer_id),
            "group_id": str(self.group_id),
            "role": self.role.name,
            "term": self.state.current_term,
            "leader_id": (str(self.state.leader_id)
                          if self.state.leader_id is not None else None),
        }

    def on_follower_heartbeat_ack(self, follower: FollowerInfo,
                                  ack_sink: Optional[list] = None) -> None:
        slot = self.peer_slots.get(follower.peer_id)
        if slot is not None and self.engine_slot >= 0:
            # routed as an ack event (match=-1 never regresses the scatter-
            # max) so the device-resident copy sees it without a row refresh
            if ack_sink is not None:
                ack_sink.append((self.engine_slot, slot, -1))
            else:
                self.server.engine.on_ack(self.engine_slot, slot, -1)
        # Heartbeat replies piggyback follower commitIndex: the *_COMMITTED
        # watch frontiers advance on them even with no new matches.
        if self._upkeep is not None:
            if self.watch_requests.pending_count():
                self._upkeep.mark_watch_dirty(self.upkeep_slot,
                                              self.upkeep_gen)
        else:
            self._update_watch_frontiers()

    # ------------------------------------------------- configuration change

    def on_configuration_changed(self) -> None:
        """Re-sync slots/masks/appenders after the effective conf changed
        (leader append, follower append, truncate rollback)."""
        self._assign_peer_slots()
        self._sync_conf_to_engine()
        self._ci_cache = None  # membership changed: rebuild commit infos
        # Listener promoted to voting member: voting rights begin as soon as
        # the conf entry is in the log (Raft uses a conf once appended);
        # demotion waits for commit (see _on_conf_entry_applied).
        if self.is_listener() and self.state.configuration.contains_voting(
                self.member_id.peer_id):
            self.role = RaftPeerRole.FOLLOWER
            self._engine_set_role(ROLE_FOLLOWER)
            self.reset_election_deadline()
        if self.is_leader() and self.leader_ctx is not None:
            ctx = self.leader_ctx
            next_index = self.state.log.next_index
            wanted = {p.id for p in self.state.configuration.all_peers()
                      if p.id != self.member_id.peer_id}
            for pid in wanted:
                if pid not in ctx.followers:
                    ctx.add_follower(pid, next_index)
            for pid in list(ctx.followers):
                if pid not in wanted:
                    # keep staged (pre-conf) followers; drop removed members
                    if self.pending_reconf is None:
                        asyncio.ensure_future(ctx.remove_follower(pid))

    def add_peer_for_staging(self, peer: RaftPeer) -> None:
        """Bootstrap a brand-new member before it enters the conf
        (LeaderStateImpl BootStrapProgress / addSenders for staging)."""
        assert self.leader_ctx is not None
        self.server.learn_peer_addresses([peer])
        self.leader_ctx.add_follower(peer.id, self.state.log.next_index)

    async def remove_staged_peer(self, peer_id: RaftPeerId) -> None:
        if self.leader_ctx is not None \
                and self.state.configuration.get_peer(peer_id) is None:
            await self.leader_ctx.remove_follower(peer_id)

    async def _on_conf_entry_applied(self, entry: LogEntry) -> None:
        """Leader-side joint-consensus progression: applied JOINT entry ->
        append the stable conf; applied STABLE entry -> complete the pending
        setConfiguration and step down if we were removed
        (reference LeaderStateImpl.updateConfiguration + replyPending)."""
        applied_conf = RaftConfiguration.from_entry(entry)
        state = self.state
        if self.is_leader() and self.leader_ctx is not None:
            if applied_conf.is_transitional():
                cur = state.configuration
                if cur.is_transitional() and cur.log_index == entry.index:
                    log = state.log
                    index = log.next_index
                    stable = RaftConfiguration(applied_conf.conf, None, index)
                    if self.pending_reconf is not None:
                        self.pending_reconf.final_index = index
                    stable_entry = stable.to_entry(state.current_term, index)
                    await log.append_entry(stable_entry)
                    state.apply_log_entry_configuration(stable_entry)
                    self.on_configuration_changed()
                    self._engine_update_flush()
                    self.leader_ctx.notify_appenders()
                return
            # stable conf applied while leading
            if self.pending_reconf is not None \
                    and entry.index == self.pending_reconf.final_index \
                    and not self.pending_reconf.future.done():
                self.pending_reconf.future.set_result(entry.index)
            # drop appenders of members that left (unless a reconf is still
            # staging new peers, whose appenders predate their conf entry)
            if self.pending_reconf is None \
                    or self.pending_reconf.joint_index >= 0:
                wanted = {p.id for p in state.configuration.all_peers()}
                for pid in list(self.leader_ctx.followers):
                    if pid not in wanted:
                        await self.leader_ctx.remove_follower(pid)
        if applied_conf.is_transitional():
            return
        # Role reconciliation against the committed stable conf (every role):
        # a member demoted from the voting set — or removed outright — drops
        # leadership/candidacy only once the conf is committed (Raft §6:
        # a removed leader steps down after C_new is committed).
        me = self.member_id.peer_id
        voting = applied_conf.contains_voting(me)
        in_conf = applied_conf.get_peer(me) is not None
        if not voting and not self.is_listener():
            if self.is_leader() or self.is_candidate():
                await self.change_to_follower(
                    state.current_term, None,
                    reason="no longer a voting member")
            if in_conf:
                # demoted to listener: replicate, never vote or campaign
                self.role = RaftPeerRole.LISTENER
                self._engine_set_role(ROLE_LISTENER)
                if self.engine_slot >= 0:
                    from ratis_tpu.engine.state import NO_DEADLINE
                    self.server.engine.state.election_deadline_ms[
                        self.engine_slot] = NO_DEADLINE
                    self.server.engine.state.mark_dirty(self.engine_slot)

    # ------------------------------------------------------- client path

    def update_commit_info(self, peer_id: RaftPeerId, commit: int) -> None:
        if commit > self._commit_info.get(peer_id, -1):
            self._commit_info[peer_id] = commit
            self._ci_cache = None

    def get_commit_infos(self) -> tuple:
        """Cluster-wide commit picture for client replies
        (reference CommitInfoProto list on RaftClientReply).  Memoized:
        every AppendEntries build and client reply reads this, so rebuilding
        per call would tax the hot replication path."""
        own = self.state.log.get_last_committed_index()
        cache = self._ci_cache
        if cache is not None and cache[0] == own:
            return cache[1]
        from ratis_tpu.protocol.requests import CommitInfo
        self.update_commit_info(self.member_id.peer_id, own)
        known = {p.id for p in self.state.configuration.all_peers()}
        infos = tuple(CommitInfo(pid, idx)
                      for pid, idx in sorted(self._commit_info.items(),
                                             key=lambda kv: kv[0].id)
                      if pid in known)
        wire = tuple((str(c.server), c.commit_index) for c in infos)
        self._ci_cache = (own, infos, wire)
        return infos

    def get_commit_infos_wire(self) -> tuple:
        """(peer_id_str, commit) tuples for the AppendEntries piggyback."""
        self.get_commit_infos()
        return self._ci_cache[2]

    async def submit_client_request(self, req: RaftClientRequest) -> RaftClientReply:
        self.metrics.num_requests.inc()
        if self._hibernating or self._quiet_sweeps:
            self.wake_from_hibernation("client request")
        elif not self.is_leader() and self.engine_slot >= 0:
            # A hibernated group's follower contacted by a client: if the
            # leader is alive, the client's retry TO the leader wakes the
            # group (heartbeats resume and re-arm us), so the FIRST contact
            # only records a nudge.  Only a second contact after a full
            # election timeout of continued silence re-arms the timer —
            # that is the dead-leader case, and the group must become
            # electable again.  Re-arming eagerly would let every client
            # probe of a healthy sleeping group trigger an election.
            if self._hibernated_follower and self.is_follower():
                now = asyncio.get_running_loop().time()
                if self._wake_nudge_s and (now - self._wake_nudge_s
                                           > self._election_timeout_min_s):
                    self._wake_nudge_s = 0.0
                    self.reset_election_deadline()
                elif not self._wake_nudge_s:
                    self._wake_nudge_s = now
        if req.replied_call_ids:
            # piggybacked retry-cache GC (RaftClientImpl.RepliedCallIds)
            self.retry_cache.evict_replied(req.client_id.to_bytes(),
                                           req.replied_call_ids)
        reply = await self._submit_client_request_impl(req)
        if reply is DEFERRED_REPLY:
            # deferred-reply fast path: the fan-out callback attaches the
            # commit infos and hands the real reply to the transport sink
            return reply
        if reply is not None and not reply.commit_infos:
            import dataclasses
            reply = dataclasses.replace(reply,
                                        commit_infos=self.get_commit_infos())
        return reply

    async def _submit_client_request_impl(self, req: RaftClientRequest
                                          ) -> RaftClientReply:
        t = req.type.type
        if t == RequestType.WRITE:
            if req.slider_seq_num >= 0:
                return await self._write_ordered(req)
            return await self._write_async(req)
        if t == RequestType.READ:
            return await self._read_async(req)
        if t == RequestType.STALE_READ:
            return await self._stale_read_async(req)
        if t == RequestType.WATCH:
            return await self._watch_async(req)
        if t == RequestType.MESSAGE_STREAM:
            return await self._message_stream_async(req)
        if t == RequestType.DATA_STREAM:
            # the submit of a completed DataStream rides the write path; the
            # streamed bytes are linked at apply (DataStreamManagement)
            return await self._write_async(req)
        if t == RequestType.SET_CONFIGURATION:
            from ratis_tpu.server import admin
            return await admin.set_configuration(self, req)
        if t == RequestType.TRANSFER_LEADERSHIP:
            from ratis_tpu.server import admin
            return await admin.transfer_leadership(self, req)
        if t == RequestType.SNAPSHOT_MANAGEMENT:
            return await self._snapshot_mgmt_async(req)
        if t == RequestType.LEADER_ELECTION_MANAGEMENT:
            return await self._election_mgmt_async(req)
        if t == RequestType.GROUP_INFO:
            return self._group_info(req)
        return RaftClientReply.failure_reply(
            req, RaftException(f"unsupported request type {t.name}"))

    def _check_leader(self, req: RaftClientRequest) -> Optional[RaftClientReply]:
        if not self.is_leader() or self.leader_ctx is None:
            return RaftClientReply.failure_reply(
                req, NotLeaderException(self.member_id, self.get_leader_peer(),
                                        self.state.configuration.all_peers()))
        if self.stepping_down:
            return RaftClientReply.failure_reply(
                req, LeaderSteppingDownException(
                    f"{self.member_id} is stepping down (leadership transfer)"))
        if not self.leader_ctx.leader_ready.done():
            # Leader until the startup entry commits: retryable not-ready.
            if self._applied_index < self.leader_ctx.startup_index:
                return RaftClientReply.failure_reply(
                    req, LeaderNotReadyException(self.member_id))
        return None

    async def _write_ordered(self, req: RaftClientRequest) -> RaftClientReply:
        """Ordered-async server side (reference
        GrpcClientProtocolService.java:151 + SlidingWindow.Server): requests
        from one client are released to the log-append path strictly in
        seqNum order; the window advances as soon as a request is APPENDED
        (not committed), so ordering costs no pipelining."""
        err = self._check_leader(req)
        if err is not None:
            return err  # fast-fail: only a live leader parks requests
        cid = req.client_id.to_bytes()
        win = self._client_windows.get(cid)
        if win is None:
            from ratis_tpu.util.sliding_window import SlidingWindowServer
            win = SlidingWindowServer(self._ordered_submit,
                                      name=str(req.client_id),
                                      on_drop=self._on_window_drop)
            self._client_windows[cid] = win
        win.last_used = asyncio.get_running_loop().time()
        if self._upkeep is None:
            self._sweep_client_windows()
        else:
            # array mode: no per-write census walk — the plane's CH_WINDOW
            # deadline sweeps once the census crosses the threshold
            self.upkeep_arm_window()
        fut = asyncio.get_running_loop().create_future()
        accepted = await win.receive(req.slider_seq_num, req.slider_first,
                                     (req, fut))
        if not accepted:
            # duplicate of an already-released seq: the retry cache answers
            # it (same call_id as the original execution)
            return await self._write_async(req)
        return await fut

    def _sweep_client_windows(self, force: bool = False) -> None:
        """Idle-window GC: the reference ties window lifetime to the client
        stream; with per-request transports we expire instead."""
        if not force and len(self._client_windows) <= 256:
            return
        now = asyncio.get_running_loop().time()
        for cid, win in list(self._client_windows.items()):
            if win.pending_count() == 0 \
                    and now - getattr(win, "last_used", 0.0) > 120.0:
                del self._client_windows[cid]

    async def _ordered_submit(self, item) -> None:
        """SlidingWindowServer process callback: run the write, but return
        (releasing the next seqNum) as soon as this request has been
        appended to the log — commit/apply completes the reply later."""
        req, fut = item
        submitted = asyncio.get_running_loop().create_future()

        def on_submitted() -> None:
            if not submitted.done():
                submitted.set_result(None)

        async def run() -> None:
            try:
                reply = await self._write_async(req, on_submitted=on_submitted)
                if not fut.done():
                    if reply is not DEFERRED_REPLY:
                        # legacy chain hop #2: this resolution wakes the
                        # parked _write_ordered handler (deferred replies
                        # resolve the handler at APPEND time — off the
                        # commit latency path, so not a commit->reply hop)
                        hop("reply_window")
                    fut.set_result(reply)
            except asyncio.CancelledError:
                # division closing: unblock the handler awaiting fut
                if not fut.done():
                    fut.cancel()
                raise
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
            finally:
                on_submitted()

        self._spawn_bg(run())
        await submitted

    def _on_window_drop(self, item) -> None:
        """A window rebase discarded a parked request whose seq can never be
        released (its client already moved on): resolve the reply future so
        the handler coroutine doesn't leak."""
        req, fut = item
        if not fut.done():
            fut.set_result(RaftClientReply.failure_reply(
                req, RaftException(
                    "superseded: ordered window rebased past this seqNum")))

    def _drain_client_windows(self, exception: Exception) -> None:
        """Step-down/close: fail requests still parked in reorder windows."""
        for win in self._client_windows.values():
            for req, fut in win.drain_parked():
                if not fut.done():
                    fut.set_result(
                        RaftClientReply.failure_reply(req, exception))
        self._client_windows.clear()

    async def _write_async(self, req: RaftClientRequest,
                           on_submitted=None) -> RaftClientReply:
        err = self._check_leader(req)
        if err is not None:
            return err
        # Retry-cache dedupe (RaftServerImpl.submitClientRequestAsync:937):
        # a retried (clientId, callId) — including after failover — waits on
        # the original attempt's reply instead of re-executing.  Loop until we
        # either own a fresh entry or return a completed one: when a failed
        # attempt cancels its entry, exactly ONE concurrent retry wins the
        # replacement entry and re-executes.
        while True:
            cache_entry, is_new = self.retry_cache.get_or_create(
                req.client_id.to_bytes(), req.call_id)
            if is_new:
                self.metrics.retry_cache_miss.inc()
                break
            self.metrics.retry_cache_hit.inc()
            if on_submitted is not None:
                on_submitted()  # the original attempt already appended it
            try:
                return await asyncio.shield(cache_entry.future)
            except asyncio.CancelledError:
                if not cache_entry.future.cancelled():
                    raise  # our caller was cancelled, not the entry

        deliver = None
        sink = reply_sink_of(req) if self._reply_fanout else None
        if sink is not None:
            # Deferred-reply fast path: the tail of this method (cache
            # completion, write-index cache, commit-info piggyback) runs
            # as ONE synchronous callback from the waterline fan-out, and
            # the reply lands in the transport's per-connection batcher —
            # no per-request future-resume chain between commit and wire.
            def deliver(reply, *, _entry=cache_entry, _req=req,
                        _sink=sink):
                import dataclasses  # local like the other reply-path uses
                try:
                    if reply.success:
                        _entry.complete(reply)
                        self.write_index_cache.put(
                            _req.client_id.to_bytes(), reply.log_index)
                    else:
                        self.metrics.num_failed.inc()
                        _entry.fail()  # let a retry re-execute
                    if not reply.commit_infos:
                        reply = dataclasses.replace(
                            reply, commit_infos=self.get_commit_infos())
                    _sink(reply)
                except Exception:
                    LOG.exception("%s deferred reply delivery failed",
                                  self.member_id)
        with self.metrics.write_timer.time():
            try:
                reply = await self._write_impl(req, on_submitted, deliver)
            except asyncio.CancelledError:
                cache_entry.fail()
                raise
            except Exception as e:
                # e.g. RaftLogIOException from a latched-dead log: the cache
                # entry must resolve or every retry of this call_id hangs on
                # its future forever.
                cache_entry.fail()
                self.metrics.num_failed.inc()
                exc = e if isinstance(e, RaftException) \
                    else RaftException(str(e))
                return RaftClientReply.failure_reply(req, exc)
        if reply is DEFERRED_REPLY:
            return reply  # the registered callback owns the tail above
        if not reply.success:
            self.metrics.num_failed.inc()
        if reply.success:
            cache_entry.complete(reply)
            self.write_index_cache.put(req.client_id.to_bytes(),
                                       reply.log_index)
        else:
            cache_entry.fail()  # let a retry re-execute
        return reply

    async def _write_impl(self, req: RaftClientRequest,
                          on_submitted=None, deliver=None) -> RaftClientReply:
        await injection.execute(injection.APPEND_TRANSACTION, self.member_id,
                                req.client_id)
        tid = req.trace_id if TRACER.enabled else 0
        t0 = TRACER.now() if tid else 0
        try:
            trx = await self.state_machine.start_transaction(req)
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, StateMachineException(str(e), cause=e))
        if trx.exception is not None:
            return RaftClientReply.failure_reply(
                req, StateMachineException(str(trx.exception),
                                           cause=trx.exception))
        trx = await self.state_machine.pre_append_transaction(trx)
        if tid:
            TRACER.record(tid, STAGE_TXN, t0, TRACER.now())

        log = self.state.log
        index = log.next_index
        entry = make_transaction_entry(self.state.current_term, index,
                                       req.client_id, req.call_id,
                                       trx.log_data or b"",
                                       sm_data=trx.sm_data,
                                       is_datastream=(req.type.type
                                                      == RequestType.DATA_STREAM))
        trx.log_entry = entry
        self.server.transactions[(self.group_id, index)] = trx
        try:
            pending = self.leader_ctx.pending.add(index, req)
        except RaftException as e:
            return RaftClientReply.failure_reply(req, e)
        # Decoupled append (VERDICT r1 item 5): return after the in-memory
        # append; the fsync overlaps the follower RPCs the appenders start
        # right below, and the flush callback advances the engine's
        # flush_index (the leader's self-slot commit input) when it lands.
        if tid:
            t0 = TRACER.now()
        await log.append_entry(entry, wait_flush=False)
        if tid:
            now = TRACER.now()
            TRACER.record(tid, STAGE_APPEND, t0, now)
            self._trace_pending[index] = (tid, now)
        self._engine_update_flush()
        self.leader_ctx.notify_appenders()
        if on_submitted is not None:
            on_submitted()  # appended: the ordered window may release the next
        if deliver is not None:
            # Deferred completion: the waterline fan-out invokes the
            # callback synchronously at commit — this coroutine is done.
            # No awaits sit between the pending registration above and
            # here, so the apply loop cannot have raced the registration.
            def _delivered(reply, *, _idx=index, _tid=tid):
                if _tid:
                    done = self._trace_applied.pop(_idx, None)
                    if done is not None:
                        # apply done -> fan-out delivery: the reply span
                        # is now the (batched) fan-out cost, not a task
                        # resume
                        TRACER.record(_tid, STAGE_REPLY, done[1],
                                      TRACER.now())
                deliver(reply)
            pending.deliver_to(_delivered)
            return DEFERRED_REPLY
        reply = await pending.future
        if tid:
            done = self._trace_applied.pop(index, None)
            if done is not None:
                # apply done -> this coroutine resumed: the reply span is
                # pure future-resolution + event-loop scheduling cost
                TRACER.record(tid, STAGE_REPLY, done[1], TRACER.now())
        return reply

    async def _read_async(self, req: RaftClientRequest) -> RaftClientReply:
        with self.metrics.read_timer.time():
            return await self._read_async_impl(req)

    async def _read_async_impl(self, req: RaftClientRequest) -> RaftClientReply:
        from ratis_tpu.protocol.exceptions import ReadException, ReadIndexException
        linearizable = (self.read_option ==
                        RaftServerConfigKeys.Read.Option.LINEARIZABLE
                        and not req.type.read_nonlinearizable)

        # Read-after-write consistency (reference WriteIndexCache): wait for
        # this client's last write to be applied locally first.
        if req.type.read_after_write_consistent:
            widx = self.write_index_cache.get(req.client_id.to_bytes())
            if widx >= 0:
                try:
                    await self.applied_waiters.wait_applied(
                        widx, self.read_timeout_s)
                except asyncio.TimeoutError:
                    return RaftClientReply.failure_reply(
                        req, ReadException(
                            f"read-after-write: write index {widx} not applied "
                            f"within {self.read_timeout_s}s"))

        if not linearizable:
            err = self._check_leader(req)
            if err is not None:
                return err
            return await self._query(req)

        # Linearizable (Raft §6.4): get a readIndex, wait until applied.
        try:
            if self.is_leader():
                # Leader-ready gate: a fresh leader's commitIndex may lag
                # acknowledged writes until its own-term startup entry
                # commits; serving readIndex before that breaks
                # linearizability.
                err = self._check_leader(req)
                if err is not None:
                    return err
                read_index = await self._leader_read_index()
            else:
                read_index = await self._follower_read_index(req)
            await self.applied_waiters.wait_applied(read_index,
                                                    self.read_timeout_s)
        except RaftException as e:
            return RaftClientReply.failure_reply(req, e)
        except asyncio.TimeoutError:
            return RaftClientReply.failure_reply(
                req, ReadIndexException("read index wait timed out"))
        return await self._query(req)

    async def _query(self, req: RaftClientRequest) -> RaftClientReply:
        try:
            result = await self.state_machine.query(req.message)
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, StateMachineException(str(e), cause=e))
        return RaftClientReply.success_reply(req, message=result,
                                             log_index=self._applied_index)

    async def _leader_read_index(self) -> int:
        """readIndex = commitIndex, after confirming we are still the leader
        (ReadIndexHeartbeats.java:40); the heartbeat round is skipped while
        the lease is valid (LeaderLease.java:36)."""
        from ratis_tpu.protocol.exceptions import ReadIndexException
        if self.leader_ctx is None:
            raise ReadIndexException("not leader")
        read_index = self.state.log.get_last_committed_index()
        if self.lease.enabled and self._lease_valid():
            return read_index
        # Batched confirmation (serving plane): every group with pending
        # reads on this shard shares one zero-entry envelope sweep per
        # destination instead of a per-group heartbeat round.
        serving = getattr(self.server, "serving", None)
        scheduler = getattr(serving, "read_batch", None)
        if scheduler is not None:
            await asyncio.shield(scheduler.confirm(self))
            return read_index
        # Share one in-flight confirmation round among concurrent reads
        # (reference ReadIndexHeartbeats.AppendEntriesListeners:126).
        if self._confirm_inflight is None or self._confirm_inflight.done():
            self._confirm_inflight = asyncio.create_task(
                self._confirm_leadership())
        await asyncio.shield(self._confirm_inflight)
        return read_index

    def _lease_valid(self) -> bool:
        from ratis_tpu.ops import reference as ref
        st = self.server.engine.state
        slot = self.engine_slot
        if slot < 0:
            return False
        expiry = ref.lease_expiry(
            st.last_ack_ms[slot].tolist(), int(st.self_slot[slot]),
            st.conf_cur[slot].tolist(), st.conf_old[slot].tolist(),
            int(self.lease.lease_ms))
        return self.server.engine.clock.now_ms() < expiry

    async def _confirm_leadership(self) -> None:
        """One empty-append round; a majority of acks proves leadership
        (ReadIndexHeartbeats' AppendEntriesListeners:126)."""
        from ratis_tpu.protocol.exceptions import ReadIndexException
        conf = self.state.configuration
        others = [p for p in conf.voting_peers()
                  if p.id != self.member_id.peer_id]
        if not others:
            return
        need = len(conf.voting_peers()) // 2 + 1 - 1  # minus self
        log = self.state.log
        prev = log.get_last_entry_term_index()

        async def _hb(peer):
            req = AppendEntriesRequest(
                RaftRpcHeader(self.member_id.peer_id, peer.id, self.group_id),
                self.state.current_term, prev, (),
                log.get_last_committed_index())
            reply = await self.server.send_server_rpc(peer.id, req)
            return reply.result == AppendResult.SUCCESS \
                or reply.result == AppendResult.INCONSISTENCY

        tasks = [asyncio.create_task(_hb(p)) for p in others]
        acks = 0
        try:
            for fut in asyncio.as_completed(tasks, timeout=self.read_timeout_s):
                try:
                    if await fut:
                        acks += 1
                except Exception:
                    continue
                if acks >= need:
                    return
        except asyncio.TimeoutError:
            pass
        finally:
            for t in tasks:
                t.cancel()
        if acks < need:
            raise ReadIndexException(
                f"leadership not confirmed: {acks}/{need} acks")

    async def _follower_read_index(self, req: RaftClientRequest) -> int:
        """Follower-served linearizable read: ask the leader for a readIndex
        (reference readIndexAsync, RaftServerAsynchronousProtocol)."""
        from ratis_tpu.protocol.exceptions import ReadIndexException
        from ratis_tpu.protocol.raftrpc import ReadIndexRequest
        leader = self.state.leader_id
        if leader is None:
            raise NotLeaderException(self.member_id, None,
                                     self.state.configuration.all_peers())
        rreq = ReadIndexRequest(RaftRpcHeader(self.member_id.peer_id, leader,
                                              self.group_id))
        reply = await self.server.send_server_rpc(leader, rreq)
        if not reply.ok:
            raise ReadIndexException(f"leader {leader} rejected readIndex")
        return reply.read_index

    async def _watch_async(self, req: RaftClientRequest) -> RaftClientReply:
        """Watch an index for a replication level (WatchRequests.java:42)."""
        err = self._check_leader(req)
        if err is not None:
            return err
        # refresh stored frontiers first: the ack-path updates skip while no
        # watches are pending, so they may be stale at registration
        self._update_watch_frontiers(force=True)
        try:
            with self.metrics.watch_timer.time():
                frontier = await self.watch_requests.watch(
                    req.type.watch_index, req.type.watch_replication,
                    req.call_id)
        except RaftException as e:
            return RaftClientReply.failure_reply(req, e)
        return RaftClientReply.success_reply(req, log_index=frontier)

    async def _message_stream_async(self, req: RaftClientRequest) -> RaftClientReply:
        """MessageStream sub-request accumulation
        (RaftServerImpl.messageStreamAsync:1111 + MessageStreamRequests)."""
        err = self._check_leader(req)
        if err is not None:
            return err
        try:
            if not req.type.end_of_request:
                self.message_stream_requests.stream_async(req)
                return RaftClientReply.success_reply(req)
            write_req = \
                self.message_stream_requests.stream_end_of_request_async(req)
        except RaftException as e:
            return RaftClientReply.failure_reply(req, e)
        if write_req is self.message_stream_requests.RETIRED:
            # re-sent end-of-request: the assembled write already ran (or is
            # still replicating); only the retry cache may answer —
            # re-executing with just the final chunk would corrupt the
            # payload.  Await an in-flight original like _write_async does.
            entry = self.retry_cache.get(req.client_id.to_bytes(),
                                         req.call_id)
            if entry is not None and not entry.future.cancelled():
                try:
                    return await asyncio.shield(entry.future)
                except asyncio.CancelledError:
                    if not entry.future.cancelled():
                        raise  # our caller was cancelled, not the entry
            return RaftClientReply.failure_reply(req, StreamException(
                f"stream {req.type.stream_id}: already assembled but the "
                "reply is no longer cached; restart the stream"))
        return await self._write_async(write_req)

    async def _stale_read_async(self, req: RaftClientRequest) -> RaftClientReply:
        min_index = req.type.stale_read_min_index
        if self._applied_index < min_index:
            return RaftClientReply.failure_reply(
                req, StaleReadException(
                    f"applied index {self._applied_index} < requested {min_index}"))
        try:
            result = await self.state_machine.query_stale(req.message, min_index)
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, StateMachineException(str(e), cause=e))
        return RaftClientReply.success_reply(req, message=result,
                                             log_index=self._applied_index)

    # ----------------------------------------------------------- admin ops

    async def _snapshot_mgmt_async(self, req: RaftClientRequest
                                   ) -> RaftClientReply:
        """Client-triggered snapshot create
        (SnapshotManagementRequestHandler): skip when the latest snapshot is
        within the creation gap of the applied index."""
        from ratis_tpu.protocol.admin import SnapshotManagementArguments
        try:
            args = SnapshotManagementArguments.from_payload(req.message.content)
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, RaftException(f"bad snapshotManagement payload: {e}"))
        gap = args.creation_gap
        if gap <= 0:
            gap = self.server.properties.get_int(
                RaftServerConfigKeys.Snapshot.CREATION_GAP_KEY,
                RaftServerConfigKeys.Snapshot.CREATION_GAP_DEFAULT)
        snap = self.state_machine.get_latest_snapshot()
        if snap is not None and self._applied_index - snap.index < gap:
            return RaftClientReply.success_reply(req, log_index=snap.index)
        try:
            index = await self.take_snapshot_async()
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, StateMachineException(str(e), cause=e))
        return RaftClientReply.success_reply(req, log_index=index)

    async def _election_mgmt_async(self, req: RaftClientRequest
                                   ) -> RaftClientReply:
        """Pause/resume this server's candidacy
        (LeaderElectionManagementRequest; RaftServerImpl
        leaderElectionManagementAsync:1285)."""
        from ratis_tpu.protocol.admin import (LeaderElectionManagementArguments,
                                              LeaderElectionManagementOp)
        try:
            args = LeaderElectionManagementArguments.from_payload(
                req.message.content)
        except Exception as e:
            return RaftClientReply.failure_reply(
                req, RaftException(f"bad leaderElectionManagement payload: {e}"))
        if args.op == LeaderElectionManagementOp.PAUSE:
            self._election_paused = True
        else:
            self._election_paused = False
            self.reset_election_deadline()
        return RaftClientReply.success_reply(req)

    def _group_info(self, req: RaftClientRequest) -> RaftClientReply:
        """GroupInfoRequest (reference GroupInfoReply + RoleInfoProto:537)."""
        from ratis_tpu.protocol.admin import GroupInfoReplyData
        conf = self.state.configuration
        data = GroupInfoReplyData(
            group=RaftGroup.value_of(self.group_id, conf.all_peers()),
            role=self.role.name,
            term=self.state.current_term,
            leader_id=str(self.state.leader_id)
            if self.state.leader_id is not None else None,
            commit_index=self.state.log.get_last_committed_index(),
            applied_index=self._applied_index,
            is_leader_ready=(self.leader_ctx is not None
                             and self.leader_ctx.leader_ready.done()))
        return RaftClientReply.success_reply(
            req, message=Message(data.to_payload()),
            log_index=self._applied_index)

    # ----------------------------------------------------------- apply loop

    async def _apply_loop(self) -> None:
        """StateMachineUpdater (reference StateMachineUpdater.java:60): waits
        for the commit index to advance, applies entries in order, completes
        pending client futures."""
        sm = self.state_machine
        while self._running:
            log = self.state.log
            # clear BEFORE the commit check: a wake landing between check
            # and clear would otherwise be lost, and this wait has no
            # timeout (a poll timer per division is real churn at thousands
            # of co-hosted groups)
            self._apply_wake.clear()
            if self._applied_index >= log.get_last_committed_index():
                await self._apply_wake.wait()
            committed = log.get_last_committed_index()
            # Waterline reply fan-out (raft.tpu.replication.reply-fanout):
            # the batch's client waiters are resolved in ONE pass after the
            # applied frontier reaches the waterline, instead of one
            # per-entry wakeup chain each (bounded: an oversized backlog
            # flushes every 64 entries so first replies never wait out a
            # huge catch-up batch).
            batch: Optional[list] = [] if self._reply_fanout else None
            while self._applied_index < committed:
                index = self._applied_index + 1
                entry = log.get(index)
                if entry is None:
                    # purged or not yet local (snapshot install in
                    # progress): back off instead of spinning on the gap
                    if batch:
                        self._flush_reply_batch(batch)
                        batch = []
                    await asyncio.sleep(0.05)
                    break
                await self._apply_one(entry, batch)
                self._applied_index = index
                sm.update_last_applied_term_index(entry.term, entry.index)
                if batch is not None and len(batch) >= 64:
                    self._flush_reply_batch(batch)
                    batch = []
            if batch:
                self._flush_reply_batch(batch)
            self._engine_set_applied()
            self.applied_waiters.advance(self._applied_index)
            log.evict_cache(self._applied_index)
            if self.is_leader() and self.leader_ctx is not None \
                    and not self.leader_ctx.leader_ready.done() \
                    and self._applied_index >= self.leader_ctx.startup_index >= 0:
                self.leader_ctx.leader_ready.set_result(True)
                await sm.notify_leader_ready()
            if self._should_auto_snapshot():
                try:
                    await self.take_snapshot_async()
                except Exception:
                    LOG.exception("%s auto snapshot failed", self.member_id)
            # Sweep expired retry-cache entries on an interval, not per batch.
            import time as _time
            now = _time.monotonic()
            if self._upkeep is not None:
                # array mode: no per-division interval clock — the shared
                # CH_CACHE waterline fires the sweep; this is just the O(1)
                # arm check after a batch may have created the first entry
                self.upkeep_arm_cache(now)
            elif now - self._last_cache_sweep > self.retry_cache.expiry_s / 4:
                self._last_cache_sweep = now
                self.retry_cache.sweep()
                # same cadence for the write-index cache: the lazy get()
                # path never evicts ids that stop querying
                self.write_index_cache.sweep(now)

    def _flush_reply_batch(self, batch: list) -> None:
        """One waterline fan-out pass: resolve every client waiter the
        applied batch completed.  Sink-carrying requests deliver straight
        into their transport's per-connection reply batcher (synchronous
        callback, no task resume); legacy waiters get their futures
        resolved here — either way the whole batch is one scheduled unit,
        not one wakeup chain per request (hops metric site
        ``reply_batch``; span ``server.fanout``)."""
        hop("reply_batch")
        t0 = TRACER.now() if TRACER.enabled and TRACER.sample() else 0
        for pending, exception, message, index in batch:
            try:
                if exception is not None:
                    pending.fail(exception)
                else:
                    pending.set_reply(RaftClientReply.success_reply(
                        pending.request, message=message or Message.EMPTY,
                        log_index=index))
            except Exception:
                LOG.exception("%s reply fan-out failed", self.member_id)
        if t0:
            TRACER.record(0, STAGE_FANOUT, t0, TRACER.now(),
                          tag=len(batch))

    async def _apply_one(self, entry: LogEntry,
                         reply_batch: Optional[list] = None) -> None:
        sm = self.state_machine
        reply_message: Optional[Message] = None
        exception: Optional[Exception] = None
        trace = (self._trace_pending.pop(entry.index, None)
                 if self._trace_pending else None)
        if trace is not None:
            # close the replicate span (append done -> apply starts: quorum
            # wait + apply-queue wait) and open the apply span
            t_apply0 = TRACER.now()
            TRACER.record(trace[0], STAGE_REPLICATE, trace[1], t_apply0)
        if entry.kind == LogEntryKind.STATE_MACHINE:
            trx = self.server.transactions.pop((self.group_id, entry.index), None)
            if trx is None or trx.log_entry is None \
                    or trx.log_entry.term_index() != entry.term_index():
                trx = TransactionContext(log_entry=entry)
            # DataStream link (StateMachine.DataApi.link, §3.5): tie the
            # bytes this peer streamed to the committed entry before apply.
            # A replica that holds no local stream for a DATA_STREAM entry
            # (crashed between stream CLOSE and apply, or outside the routing
            # table) still gets data_link(None, entry) so the StateMachine can
            # detect the miss and fetch/repair — the reference passes a null
            # stream for exactly this case.
            if entry.smlog is not None:
                link = None
                if self.server.datastream is not None:
                    link = self.server.datastream.take_link(
                        entry.smlog.client_id, entry.smlog.call_id)
                if link is not None or entry.smlog.is_datastream:
                    try:
                        await sm.data_link(
                            link.local if link is not None else None, entry)
                    except Exception:
                        LOG.exception("%s data_link failed", self.member_id)
            try:
                # applyTransactionSerial runs strictly in log order ahead of
                # applyTransaction (StateMachine.java:565: the serial hook
                # for state machines that parallelize the main apply); the
                # updater daemon here is itself serial, so the pair runs
                # back-to-back per entry in index order.
                trx = await sm.apply_transaction_serial(trx)
                reply_message = await sm.apply_transaction(trx)
                self.sm_metrics.applied_count.inc()
            except Exception as e:
                exception = StateMachineException(str(e), cause=e)
            # Populate the retry cache on EVERY role at apply time so a
            # request retried against the post-failover leader is deduped
            # (reference RetryCacheImpl failover-safe dedupe).
            if entry.smlog is not None and exception is None:
                cache_entry = self.retry_cache.get_or_create_on_apply(
                    entry.smlog.client_id, entry.smlog.call_id)
                from ratis_tpu.protocol.ids import ClientId
                cache_entry.complete(RaftClientReply(
                    ClientId.value_of(entry.smlog.client_id),
                    self.member_id.peer_id, self.group_id,
                    entry.smlog.call_id, True,
                    message=reply_message or Message.EMPTY,
                    log_index=entry.index))
        elif entry.kind == LogEntryKind.CONFIGURATION:
            if self.storage is not None:
                await asyncio.to_thread(self.storage.persist_conf_entry, entry)
            await sm.notify_configuration_changed(
                entry.term, entry.index, self.state.configuration)
            await self._on_conf_entry_applied(entry)
        if self._sm_wants_term_index:
            await sm.notify_term_index_updated(entry.term, entry.index)
        if trace is not None:
            now = TRACER.now()
            TRACER.record(trace[0], STAGE_APPLY, t_apply0, now)
            self._trace_applied[entry.index] = (trace[0], now)

        if self.is_leader() and self.leader_ctx is not None:
            pending = self.leader_ctx.pending.pop(entry.index)
            if pending is not None:
                if reply_batch is not None:
                    # waterline fan-out: the apply loop resolves the whole
                    # batch in one pass (see _flush_reply_batch)
                    reply_batch.append((pending, exception, reply_message,
                                        entry.index))
                elif exception is not None:
                    pending.fail(exception)
                else:
                    pending.set_reply(RaftClientReply.success_reply(
                        pending.request, message=reply_message or Message.EMPTY,
                        log_index=entry.index))
