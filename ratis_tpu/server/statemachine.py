"""StateMachine SPI: the application-extension interface.

Capability parity with the reference StateMachine
(ratis-server-api/src/main/java/org/apache/ratis/statemachine/StateMachine.java:57):
lifecycle (initialize:437 / pause:449 / reinitialize:456), queries (query:492,
queryStale:505), the transaction pipeline (startTransaction:520,
preAppendTransaction:546, applyTransaction:592), snapshotting
(takeSnapshot, getLatestSnapshot:487), and the optional event sub-APIs
(EventApi:158, LeaderEventApi:237, FollowerEventApi:271).  asyncio-native:
apply/query return awaitables so state machines can do real I/O.
"""

from __future__ import annotations

import asyncio
import dataclasses
import pathlib
from typing import Any, Iterable, Optional

from ratis_tpu.protocol.group import RaftGroup, RaftGroupMemberId
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.requests import RaftClientRequest
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, INVALID_TERM, TermIndex
from ratis_tpu.util.lifecycle import LifeCycle, LifeCycleState


@dataclasses.dataclass(frozen=True)
class SnapshotFileInfo:
    """One file of a snapshot (path + MD5), cf. FileInfo in the reference."""

    path: str
    digest: bytes = b""


@dataclasses.dataclass(frozen=True)
class SnapshotInfo:
    """Term/index + files of one snapshot (reference SnapshotInfo /
    SingleFileSnapshotInfo / FileListSnapshotInfo)."""

    term_index: TermIndex
    files: tuple[SnapshotFileInfo, ...] = ()

    @property
    def index(self) -> int:
        return self.term_index.index


@dataclasses.dataclass
class TransactionContext:
    """Carries one transaction from startTransaction through apply
    (reference TransactionContextImpl, ratis-server/.../statemachine/impl/)."""

    client_request: Optional[RaftClientRequest] = None
    log_entry: Optional[LogEntry] = None
    state_machine_context: Any = None  # app-private scratch
    exception: Optional[Exception] = None
    # Data the SM wants logged (may differ from the request message)
    log_data: Optional[bytes] = None
    sm_data: Optional[bytes] = None
    should_commit: bool = True


class StateMachineStorage:
    """Where a state machine keeps its snapshots
    (reference StateMachineStorage / SimpleStateMachineStorage)."""

    SNAPSHOT_PREFIX = "snapshot"

    def __init__(self):
        self._dir: Optional[pathlib.Path] = None

    def init(self, sm_dir: "str | pathlib.Path") -> None:
        self._dir = pathlib.Path(sm_dir)
        self._dir.mkdir(parents=True, exist_ok=True)

    @property
    def directory(self) -> Optional[pathlib.Path]:
        return self._dir

    def snapshot_path(self, term: int, index: int) -> pathlib.Path:
        # file pattern snapshot.<term>_<index>, cf. SimpleStateMachineStorage
        assert self._dir is not None, "storage not initialized"
        return self._dir / f"{self.SNAPSHOT_PREFIX}.{term}_{index}"

    def find_latest_snapshot(self) -> Optional[SnapshotInfo]:
        if self._dir is None or not self._dir.exists():
            return None
        best: Optional[tuple[int, int, pathlib.Path]] = None
        for f in self._dir.iterdir():
            name = f.name
            if not name.startswith(self.SNAPSHOT_PREFIX + "."):
                continue
            try:
                term_s, index_s = name[len(self.SNAPSHOT_PREFIX) + 1:].split("_")
                term, index = int(term_s), int(index_s)
            except ValueError:
                continue
            if best is None or index > best[1]:
                best = (term, index, f)
        if best is None:
            return None
        return SnapshotInfo(TermIndex(best[0], best[1]),
                            (SnapshotFileInfo(str(best[2])),))

    def clean_old_snapshots(self, retention: int) -> None:
        if self._dir is None or retention < 0:
            return
        snaps = []
        for f in self._dir.iterdir():
            if f.name.startswith(self.SNAPSHOT_PREFIX + "."):
                try:
                    _, index_s = f.name[len(self.SNAPSHOT_PREFIX) + 1:].split("_")
                    snaps.append((int(index_s), f))
                except ValueError:
                    continue
        for _, f in sorted(snaps)[:-retention] if retention > 0 else []:
            f.unlink(missing_ok=True)


class DataChannel:
    """Destination of one DataStream's bytes
    (reference StateMachine.DataChannel:302 — a WritableByteChannel the SM
    owns, e.g. an open file)."""

    async def write(self, data: bytes) -> int:
        raise NotImplementedError

    async def force(self, metadata: bool = False) -> None:
        """fsync-equivalent (DataChannel.force)."""

    async def close(self) -> None:
        pass


class DataStream:
    """One open stream handed out by :meth:`StateMachine.data_stream`
    (reference StateMachine.DataStream:338): the channel plus cleanup."""

    def __init__(self, channel: DataChannel, request=None) -> None:
        self.channel = channel
        self.request = request  # the header RaftClientRequest

    async def cleanup(self) -> None:
        """Discard resources after failure (DataStream.cleanUp)."""
        await self.channel.close()


class StateMachine:
    """Base class every application state machine extends.

    Matches the reference's contract: applyTransaction futures may complete
    out of band but MUST be applied in log order by the caller
    (StateMachineUpdater); query is only invoked on applied state.
    """

    def __init__(self):
        self.life_cycle = LifeCycle(type(self).__name__)
        self._storage = StateMachineStorage()
        self._last_applied: TermIndex = TermIndex.INITIAL_VALUE
        self.member_id: Optional[RaftGroupMemberId] = None

    # -- lifecycle (StateMachine.java:437-476) -------------------------------

    async def initialize(self, server, group_id: RaftGroupId,
                         storage_dir=None) -> None:
        """One SPI entry point for both durable and memory modes (the
        reference initializes the SM even with a memory log); storage_dir is
        None in memory mode and snapshot restore is skipped."""
        self.life_cycle.transition(LifeCycleState.STARTING)
        if storage_dir is not None:
            self._storage.init(pathlib.Path(storage_dir) / "sm")
            snapshot = self._storage.find_latest_snapshot()
            if snapshot is not None:
                await self.restore_from_snapshot(snapshot)
                self._last_applied = snapshot.term_index
        self.life_cycle.transition(LifeCycleState.RUNNING)

    async def pause(self) -> None:
        self.life_cycle.transition(LifeCycleState.PAUSING)
        self.life_cycle.transition(LifeCycleState.PAUSED)

    async def reinitialize(self) -> None:
        """Reload state after a snapshot was installed while paused."""
        self.life_cycle.transition(LifeCycleState.STARTING)
        snapshot = self._storage.find_latest_snapshot()
        if snapshot is not None:
            await self.restore_from_snapshot(snapshot)
            self._last_applied = snapshot.term_index
        self.life_cycle.transition(LifeCycleState.RUNNING)

    async def close(self) -> None:
        self.life_cycle.check_state_and_close(lambda: None)

    # -- storage / snapshot --------------------------------------------------

    def get_state_machine_storage(self) -> StateMachineStorage:
        return self._storage

    def get_latest_snapshot(self) -> Optional[SnapshotInfo]:
        return self._storage.find_latest_snapshot()

    async def take_snapshot(self) -> int:
        """Persist applied state; returns the snapshot's log index or
        INVALID_LOG_INDEX if unsupported (StateMachine.takeSnapshot)."""
        return INVALID_LOG_INDEX

    async def restore_from_snapshot(self, snapshot: SnapshotInfo) -> None:
        pass

    # -- applied-index bookkeeping ------------------------------------------

    def get_last_applied_term_index(self) -> TermIndex:
        return self._last_applied

    def set_last_applied_term_index(self, ti: TermIndex) -> None:
        self._last_applied = ti

    def update_last_applied_term_index(self, term: int, index: int) -> None:
        if index > self._last_applied.index:
            self._last_applied = TermIndex(term, index)

    # -- transaction pipeline (StateMachine.java:520-604) --------------------

    async def start_transaction(self, request: RaftClientRequest) -> TransactionContext:
        """Leader-side validation/transform of a client write before it is
        logged.  Default: log the message bytes verbatim."""
        return TransactionContext(client_request=request,
                                  log_data=request.message.content)

    async def pre_append_transaction(self, trx: TransactionContext) -> TransactionContext:
        return trx

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        """Apply one committed entry; returns the reply message."""
        return Message.EMPTY

    async def apply_transaction_serial(self, trx: TransactionContext) -> TransactionContext:
        return trx

    async def notify_term_index_updated(self, term: int, index: int) -> None:
        pass

    # -- queries (StateMachine.java:492-516) ---------------------------------

    async def query(self, request: Message) -> Message:
        return Message.EMPTY

    async def query_stale(self, request: Message, min_index: int) -> Message:
        return await self.query(request)

    # -- event APIs (StateMachine.java:158-299), all optional ---------------

    async def notify_leader_changed(self, member_id: RaftGroupMemberId,
                                    leader_id: RaftPeerId) -> None:
        pass

    async def notify_follower_slowness(self, leader_info, slow_peer) -> None:
        pass

    async def notify_extended_no_leader(self, role_info) -> None:
        pass

    async def notify_log_failed(self, cause: Exception, entry: Optional[LogEntry]) -> None:
        pass

    async def notify_install_snapshot_from_leader(
            self, role_info, first_available: TermIndex) -> Optional[TermIndex]:
        """Notification-mode snapshot install: app fetches state out-of-band
        and returns the installed TermIndex (StateMachine.java:293)."""
        return None

    async def notify_snapshot_installed(self, snapshot: SnapshotInfo, peer) -> None:
        pass

    async def notify_configuration_changed(self, term: int, index: int,
                                           new_conf) -> None:
        pass

    async def notify_group_remove(self) -> None:
        pass

    async def notify_server_shutdown(self, role_info, all_groups: bool) -> None:
        pass

    async def notify_leader_ready(self) -> None:
        pass

    async def notify_not_leader(self, pending_requests: Iterable) -> None:
        pass

    # ------------------------------------------------------------- DataApi
    # Optional bulk-data sub-API (reference StateMachine.DataApi:69): stream
    # bytes AROUND the raft log into SM-owned storage, then `link` ties the
    # streamed data to the log entry at apply time (§3.5 of SURVEY.md).

    async def data_stream(self, request) -> DataStream:
        """Open a DataChannel for an incoming stream (DataApi.stream)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support DataStream")

    async def data_link(self, stream: Optional[DataStream], entry) -> None:
        """Tie a completed stream's data to its committed log entry
        (DataApi.link); ``stream`` is None on peers that did not receive
        the stream (they must fetch via ordinary replication/recovery)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support DataStream")

    async def data_write(self, entry) -> None:
        """Persist SM data carried by a log entry outside the log
        (DataApi.write); default no-op."""

    async def data_flush(self, index: int) -> None:
        """Flush SM data up to a log index (DataApi.flush); default no-op."""

    def __str__(self) -> str:
        return f"{type(self).__name__}@{self.member_id}"


class BaseStateMachine(StateMachine):
    """Alias matching the reference's convenience base
    (ratis-server/.../statemachine/impl/BaseStateMachine.java); the tracking
    behavior already lives in StateMachine here."""
