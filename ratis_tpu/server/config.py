"""RaftConfiguration: the (possibly joint) peer membership of one group.

Capability parity with the reference RaftConfigurationImpl /
PeerConfiguration (ratis-server/.../impl/RaftConfigurationImpl.java,
PeerConfiguration.java:42): current + optional old conf (joint consensus),
listener exclusion from quorum, majority checks in BOTH confs
(hasMajority:265-281), and the log index the conf was committed at.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.logentry import ConfigurationEntry, LogEntry, make_config_entry
from ratis_tpu.protocol.peer import RaftPeer, RaftPeerRole


@dataclasses.dataclass(frozen=True)
class PeerConfiguration:
    """One conf: voting peers + listeners."""

    peers: tuple[RaftPeer, ...] = ()
    listeners: tuple[RaftPeer, ...] = ()

    def contains(self, peer_id: RaftPeerId) -> bool:
        return any(p.id == peer_id for p in self.peers)

    def contains_listener(self, peer_id: RaftPeerId) -> bool:
        return any(p.id == peer_id for p in self.listeners)

    def get(self, peer_id: RaftPeerId) -> Optional[RaftPeer]:
        for p in self.peers:
            if p.id == peer_id:
                return p
        for p in self.listeners:
            if p.id == peer_id:
                return p
        return None

    def size(self) -> int:
        return len(self.peers)

    def has_majority(self, voted: Iterable[RaftPeerId]) -> bool:
        voted_set = set(voted)
        count = sum(1 for p in self.peers if p.id in voted_set)
        return count >= self.size() // 2 + 1

    def majority_reject(self, rejected: Iterable[RaftPeerId]) -> bool:
        rej = set(rejected)
        count = sum(1 for p in self.peers if p.id in rej)
        return self.size() > 0 and count >= (self.size() + 1) // 2


@dataclasses.dataclass(frozen=True)
class RaftConfiguration:
    conf: PeerConfiguration
    old_conf: Optional[PeerConfiguration] = None  # set during joint consensus
    log_index: int = 0

    @staticmethod
    def from_peers(peers: Iterable[RaftPeer], log_index: int = 0) -> "RaftConfiguration":
        voting, listeners = [], []
        for p in peers:
            (listeners if p.is_listener() else voting).append(p)
        return RaftConfiguration(PeerConfiguration(tuple(voting), tuple(listeners)),
                                 None, log_index)

    @staticmethod
    def from_entry(entry: LogEntry) -> "RaftConfiguration":
        c: ConfigurationEntry = entry.conf
        old = None
        if c.old_peers or c.old_listeners:
            old = PeerConfiguration(tuple(c.old_peers), tuple(c.old_listeners))
        return RaftConfiguration(PeerConfiguration(tuple(c.peers), tuple(c.listeners)),
                                 old, entry.index)

    def to_entry(self, term: int, index: int) -> LogEntry:
        return make_config_entry(
            term, index, self.conf.peers,
            old_peers=self.old_conf.peers if self.old_conf else (),
            listeners=self.conf.listeners,
            old_listeners=self.old_conf.listeners if self.old_conf else ())

    # -- membership queries --------------------------------------------------

    def is_transitional(self) -> bool:
        return self.old_conf is not None

    def is_stable(self) -> bool:
        return self.old_conf is None

    def contains_voting(self, peer_id: RaftPeerId) -> bool:
        ok = self.conf.contains(peer_id)
        if self.old_conf is not None:
            return ok or self.old_conf.contains(peer_id)
        return ok

    def contains_current(self, peer_id: RaftPeerId) -> bool:
        return self.conf.contains(peer_id)

    def is_single_mode(self, peer_id: RaftPeerId) -> bool:
        """Candidate is the only voting member (LeaderElection singleMode)."""
        return (self.is_stable() and self.conf.size() == 1
                and self.conf.contains(peer_id))

    def get_peer(self, peer_id: RaftPeerId) -> Optional[RaftPeer]:
        p = self.conf.get(peer_id)
        if p is None and self.old_conf is not None:
            p = self.old_conf.get(peer_id)
        return p

    def all_peers(self) -> tuple[RaftPeer, ...]:
        """Every distinct member (voting + listener, both confs)."""
        seen: dict[RaftPeerId, RaftPeer] = {}
        for conf in filter(None, (self.conf, self.old_conf)):
            for p in (*conf.peers, *conf.listeners):
                seen.setdefault(p.id, p)
        return tuple(seen.values())

    def voting_peers(self) -> tuple[RaftPeer, ...]:
        seen: dict[RaftPeerId, RaftPeer] = {}
        for conf in filter(None, (self.conf, self.old_conf)):
            for p in conf.peers:
                seen.setdefault(p.id, p)
        return tuple(seen.values())

    def other_peers(self, self_id: RaftPeerId) -> tuple[RaftPeer, ...]:
        return tuple(p for p in self.all_peers() if p.id != self_id)

    def has_majority(self, voted: Iterable[RaftPeerId]) -> bool:
        voted = list(voted)
        ok = self.conf.has_majority(voted)
        if self.old_conf is not None:
            return ok and self.old_conf.has_majority(voted)
        return ok

    def majority_reject(self, rejected: Iterable[RaftPeerId]) -> bool:
        rejected = list(rejected)
        if self.conf.majority_reject(rejected):
            return True
        return self.old_conf is not None and self.old_conf.majority_reject(rejected)

    def __str__(self) -> str:
        s = f"conf@{self.log_index}:{[str(p) for p in self.conf.peers]}"
        if self.old_conf is not None:
            s += f", old:{[str(p) for p in self.old_conf.peers]}"
        return s
