"""Stall watchdog: always-on derived health signals per server.

No reference analog — the reference leaves "is the cluster making
progress?" to external alerting over its metrics; the multi-raft host
here can answer it locally, cheaply, from state it already maintains.  A
single per-server sampling task (``raft.tpu.watchdog.*``) walks the
division fleet every interval and journals structured events for three
failure shapes the perf rounds have actually hit:

- **commit-stall**: a leader's commitIndex is flat across consecutive
  samples while client requests are pending — the shape of a lost quorum
  (isolated leader, dead followers) or a wedged replication path.
- **election-churn**: server-wide election activity (timeouts fired +
  elections started) above a rate threshold — the storm signature that
  deposed thousands of leaders in rounds 4-5.
- **follower-lag**: a follower's match index more than a threshold of
  entries behind its leader's commit — a snapshot-install candidate or a
  silently failing appender.
- **stuck-lane**: a replication sender's append window stays FULL
  (every envelope slot in flight) across consecutive samples while the
  engine's commit waterline is flat — the shape of a wedged append
  round trip (frozen peer, lost replies, a lane gap that never
  recovers) under the round-9 pipelined window.

Events land in a bounded ring journal (never unbounded memory, oldest
drop first) served at ``GET /events`` by the metrics endpoint and
pretty-printed by ``python -m ratis_tpu.shell health``.  Detection
counters live in a real registry ("server" component, name "watchdog")
so the scrape carries them too.  The watchdog only READS division state
— it never awaits into division code and adds nothing to the request
path.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Optional

from ratis_tpu.metrics.registry import (MetricRegistries, MetricRegistryInfo,
                                        labeled)

LOG = logging.getLogger(__name__)

KIND_COMMIT_STALL = "commit-stall"
KIND_ELECTION_CHURN = "election-churn"
KIND_FOLLOWER_LAG = "follower-lag"
KIND_STUCK_LANE = "stuck-lane"
# Chaos campaign journaling (ratis_tpu.chaos): every DELIBERATELY injected
# fault lands in the same journal the organic detections use — paired
# with a fault-recovered event once its recovery SLO was observed — so a
# scrape of /events during a campaign shows faults and their recoveries
# interleaved with whatever the fault actually broke.  An injected-fault
# event without its recovery pair is an UNRECOVERED fault (the shell
# health subcommand exits 1 on it).
KIND_INJECTED_FAULT = "injected-fault"
KIND_FAULT_RECOVERED = "fault-recovered"
# Sustained overload (serving plane): admission control shedding above
# the configured rate for a whole interval — bounded pending is working
# as designed, but the operator should know the fleet is over capacity.
KIND_OVERLOAD = "overload"
# Grey follower (lag-ledger detector): one peer slow-but-alive across a
# threshold fraction of the groups it follows — every link up (acking
# within the up-window) yet lagging on most advancing groups at once.
# Neither commit-stall (quorum still commits) nor election-churn (the
# peer never times out) catches this shape; it is the signature partial
# failure of a fleet-wide slow disk/NIC.  Episodes pair grey-follower
# with grey-recovered through the same fault-correlation id the chaos
# campaign uses for injected faults.
KIND_GREY_FOLLOWER = "grey-follower"
KIND_GREY_RECOVERED = "grey-recovered"
# Placement controller actuations (ratis_tpu.placement): every leadership
# transfer or read-steering decision the policy loop executes journals a
# rebalance event, paired with a rebalance-done close carrying the
# outcome (success/failed/aborted) through the same fault-correlation id
# the chaos/grey pairs use.  A rebalance without its done pair is an
# actuation that never converged — the chaos rebalance_storm SLO and the
# shell health subcommand both check the pairing.
KIND_REBALANCE = "rebalance"
KIND_REBALANCE_DONE = "rebalance-done"
KINDS = (KIND_COMMIT_STALL, KIND_ELECTION_CHURN, KIND_FOLLOWER_LAG,
         KIND_STUCK_LANE, KIND_INJECTED_FAULT, KIND_FAULT_RECOVERED,
         KIND_OVERLOAD, KIND_GREY_FOLLOWER, KIND_GREY_RECOVERED,
         KIND_REBALANCE, KIND_REBALANCE_DONE)

# consecutive flat samples (with pending requests) before a commit-stall
# event is journaled: one flat interval is ordinary queueing, two is not
_STALL_ROUNDS = 2


class StallWatchdog:
    def __init__(self, server, interval_s: Optional[float] = None,
                 journal_size: Optional[int] = None,
                 lag_threshold: Optional[int] = None,
                 churn_threshold: Optional[int] = None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        keys = RaftServerConfigKeys.Watchdog
        p = server.properties
        self.server = server
        self.interval_s = (interval_s if interval_s is not None
                           else keys.interval(p).seconds)
        self.lag_threshold = (lag_threshold if lag_threshold is not None
                              else keys.follower_lag_threshold(p))
        self.churn_threshold = (churn_threshold
                                if churn_threshold is not None
                                else keys.churn_threshold(p))
        size = (journal_size if journal_size is not None
                else keys.journal_size(p))
        self.journal: collections.deque = collections.deque(
            maxlen=max(1, size))
        # monotonic event sequence id: lets /events?since=<seq> serve
        # incrementally (the flight recorder and shell poll deltas
        # instead of re-reading and re-deduping the whole ring) and
        # survives ring wraparound — a consumer that slept through a
        # full ring sees the gap in seq, not silent loss
        self._next_seq = 0
        # emit hook (flight recorder): called with each journaled record
        # AFTER it lands; exceptions are swallowed — observability of the
        # observability plane must not break detection
        self.on_event = None
        self._task: Optional[asyncio.Task] = None
        self._running = False
        # group -> (last commitIndex, consecutive flat-with-pending rounds)
        self._stall: dict = {}
        # groups currently inside a reported stall / lag episode: one event
        # per episode, not one per sample
        self._stalled: set = set()
        self._lagging: set = set()
        self._last_elections = None  # server-wide election activity count
        # stuck-lane detection: (destination, sender id) -> consecutive
        # window-full-while-commits-flat samples; one event per episode
        self._lane_full: dict = {}
        self._lane_stuck: set = set()
        self._last_commits = None  # engine commit_advances at last sample
        # sustained-overload detection: shed total at last sample + an
        # in-episode latch (one event per overload episode, not per
        # saturated interval)
        self.shed_rate_threshold = \
            RaftServerConfigKeys.Serving.overload_shed_rate(p)
        self._last_shed = None
        self._overloaded = False
        # grey-follower detection over the lag ledger (raft.tpu.lag.grey.*;
        # mutable attributes so tests/chaos retune live, like lag_threshold)
        lag_keys = RaftServerConfigKeys.Lag
        self.grey_fraction = lag_keys.grey_fraction(p)
        self.grey_min_groups = lag_keys.grey_min_groups(p)
        self.grey_rounds = lag_keys.grey_rounds(p)
        self._grey_seen: dict = {}   # peer name -> consecutive grey rounds
        self._grey: set = set()      # peers inside a reported grey episode
        self._grey_fault: dict = {}  # peer name -> episode correlation id
        self._grey_seq = 0
        info = MetricRegistryInfo(prefix=str(server.peer_id),
                                  application="ratis", component="server",
                                  name="watchdog")
        self.registry = MetricRegistries.global_registries().create(info)
        self.event_counters = {
            kind: self.registry.counter(labeled("events", kind=kind))
            for kind in KINDS}
        self.registry.gauge("journalSize", lambda: len(self.journal))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(
            self._run(), name=f"watchdog-{self.server.peer_id}")

    async def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        MetricRegistries.global_registries().remove(self.registry.info)

    # -------------------------------------------------------------- journal

    def emit(self, kind: str, group: Optional[str], detail: str,
             fault: Optional[str] = None) -> None:
        """``fault``: injected-fault correlation id — the same id on a
        KIND_INJECTED_FAULT event and its KIND_FAULT_RECOVERED pair is
        how consumers (shell health, chaos_replay) match them up."""
        record = {
            "seq": self._next_seq,
            "t": round(time.time(), 3),
            "kind": kind,
            "group": group,
            "detail": detail,
        }
        self._next_seq += 1
        if fault is not None:
            record["fault"] = fault
        self.journal.append(record)
        c = self.event_counters.get(kind)
        if c is not None:
            c.inc()
        LOG.warning("%s watchdog: %s%s: %s", self.server.peer_id, kind,
                    f" [{group}]" if group else "", detail)
        cb = self.on_event
        if cb is not None:
            try:
                cb(record)
            except Exception:
                LOG.exception("%s watchdog: on_event hook failed",
                              self.server.peer_id)

    def events(self, since: Optional[int] = None) -> list[dict]:
        """Journal contents, oldest first (the /events payload);
        ``since`` returns only records with ``seq > since``."""
        if since is None:
            return list(self.journal)
        return [e for e in self.journal if e["seq"] > since]

    @property
    def last_seq(self) -> int:
        """Newest journaled seq (-1 when nothing journaled yet)."""
        return self._next_seq - 1

    def event_count(self) -> int:
        return sum(c.count for c in self.event_counters.values())

    # ------------------------------------------------------------- sampling

    async def _run(self) -> None:
        while self._running:
            await asyncio.sleep(self.interval_s)
            try:
                self.sample()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the watchdog must never take the server down with it
                LOG.exception("%s watchdog sample failed",
                              self.server.peer_id)

    def sample(self) -> None:
        """One detection pass over the division fleet (synchronous reads
        only).  Public so tests and harnesses can force a pass."""
        elections = 0
        seen = set()
        for div in list(self.server.divisions.values()):
            gid = str(div.group_id)
            seen.add(gid)
            em = div.election_metrics
            elections += em.timeout_count.count + em.election_count.count
            if not div.is_leader() or div.leader_ctx is None:
                self._stall.pop(gid, None)
                self._stalled.discard(gid)
                continue
            commit = int(div.state.log.get_last_committed_index())
            pending = len(div.leader_ctx.pending)
            last_commit, rounds = self._stall.get(gid, (None, 0))
            if pending > 0 and commit == last_commit:
                rounds += 1
            else:
                rounds = 0
                self._stalled.discard(gid)
            self._stall[gid] = (commit, rounds)
            if rounds >= _STALL_ROUNDS and gid not in self._stalled:
                self._stalled.add(gid)
                self.emit(KIND_COMMIT_STALL, gid,
                          f"commitIndex flat at {commit} for "
                          f"{rounds * self.interval_s:.1f}s with "
                          f"{pending} pending request(s)")
        # drop bookkeeping for removed groups
        for gid in list(self._stall):
            if gid not in seen:
                self._stall.pop(gid, None)
        self._stalled &= seen
        # follower lag + grey detection read the lag ledger (one fused
        # pass + one fetch) instead of walking leader_ctx.followers
        led = self._ledger_sample()
        if led is not None:
            self._check_follower_lag(led)
            self._check_grey(led)
        # election churn: rate of new election activity per interval
        if self._last_elections is not None:
            delta = elections - self._last_elections
            if delta >= self.churn_threshold:
                self.emit(KIND_ELECTION_CHURN, None,
                          f"{delta} election timeouts/starts in "
                          f"{self.interval_s:.1f}s "
                          f"(threshold {self.churn_threshold})")
        self._last_elections = elections
        self._check_stuck_lanes()
        self._check_overload()

    def _ledger_sample(self):
        """One lag-ledger pass (engine/ledger.py); None if the engine is
        mid-teardown — detection must degrade, never throw."""
        try:
            return self.server.engine.ledger.sample()
        except Exception:
            LOG.exception("%s watchdog: ledger sample failed",
                          self.server.peer_id)
            return None

    def _check_follower_lag(self, s) -> None:
        """Follower lag from the ledger's per-group worst-link vector:
        python touches only the slots past threshold.  Same kind, same
        detail shape, same one-event-per-episode latch as the old
        division walk, so shell health and flight pairing are unchanged."""
        import numpy as np
        engine = self.server.engine
        current: set = set()
        for slot in np.nonzero(s.worst_lag > self.lag_threshold)[0]:
            listener = engine._listeners.get(int(slot))
            if listener is None:
                continue  # detached mid-pass
            gid = str(listener.group_id)
            current.add(gid)
            if gid in self._lagging:
                continue
            self._lagging.add(gid)
            peer_idx = int(s.worst_peer[slot])
            peer = (s.peer_names[peer_idx]
                    if 0 <= peer_idx < len(s.peer_names) else "?")
            self.emit(KIND_FOLLOWER_LAG, gid,
                      f"follower {peer} is {int(s.worst_lag[slot])} "
                      f"entries behind commit {int(s.commit[slot])} "
                      f"(threshold {self.lag_threshold})")
        self._lagging &= current

    def _check_grey(self, s) -> None:
        """Grey-follower episodes from the ledger's per-peer link counts:
        a peer whose links are ALL up (acking inside the up-window) while
        >= grey_fraction of its active links (up links of groups whose
        commit advanced this pass, at least grey_min_groups of them) sit
        past the lag threshold, sustained grey_rounds consecutive
        samples.  One grey-follower event per episode, paired with a
        grey-recovered event through a fault correlation id on close."""
        grey_now: set = set()
        for i, name in enumerate(s.peer_names):
            links = int(s.peer_links[i])
            if links == 0:
                continue  # self, or a peer this server leads no groups to
            down = links - int(s.peer_up[i])
            active = int(s.peer_active[i])
            laggy = int(s.peer_laggy_active[i])
            if (down == 0 and active >= self.grey_min_groups
                    and laggy / max(1, active) >= self.grey_fraction):
                grey_now.add(name)
                rounds = self._grey_seen.get(name, 0) + 1
                self._grey_seen[name] = rounds
                if rounds >= self.grey_rounds and name not in self._grey:
                    self._grey.add(name)
                    fault = f"grey-{name}-{self._grey_seq}"
                    self._grey_seq += 1
                    self._grey_fault[name] = fault
                    self.emit(
                        KIND_GREY_FOLLOWER, None,
                        f"peer {name} grey: {laggy}/{active} active "
                        f"links >= {self.server.engine.ledger.lag_threshold} "
                        f"entries behind while all {links} links are up "
                        f"(fraction {laggy / max(1, active):.2f} >= "
                        f"{self.grey_fraction:g}, max lag "
                        f"{int(s.peer_max_lag[i])})", fault=fault)
        for name in list(self._grey_seen):
            if name not in grey_now:
                self._grey_seen.pop(name, None)
        for name in list(self._grey):
            if name not in grey_now:
                self._grey.discard(name)
                self.emit(KIND_GREY_RECOVERED, None,
                          f"peer {name} recovered: grey episode over",
                          fault=self._grey_fault.pop(name, None))

    def _check_overload(self) -> None:
        """Sustained overload: the admission controller's shed rate over
        the last interval above raft.tpu.serving.overload.shed-rate.  One
        event per episode; the episode closes once a whole interval
        passes under threshold."""
        serving = getattr(self.server, "serving", None)
        if serving is None:
            return
        shed = serving.admission.shed_total
        last = self._last_shed
        self._last_shed = shed
        if last is None:
            return
        rate = (shed - last) / max(self.interval_s, 1e-9)
        if rate > self.shed_rate_threshold:
            if not self._overloaded:
                self._overloaded = True
                self.emit(KIND_OVERLOAD, None,
                          f"admission control shedding {rate:.0f} "
                          f"requests/s (threshold "
                          f"{self.shed_rate_threshold:.0f}/s); pending "
                          f"budgets holding, clients told to back off")
        else:
            self._overloaded = False

    def _check_stuck_lanes(self) -> None:
        """Stuck-lane detection (round-9 append windows): a sender whose
        envelope window stays FULL across consecutive samples while the
        engine's commit waterline is flat is a wedged round trip — under
        pipelining a healthy full window drains within one RTT, so full +
        no commit progress twice in a row is an anomaly, not load."""
        commits = int(self.server.engine.metrics.get("commit_advances", 0))
        flat = (self._last_commits is not None
                and commits == self._last_commits)
        self._last_commits = commits
        live = set()
        for (dest, _loop_key), sender in \
                list(self.server.replication._senders.items()):
            key = (dest, id(sender))
            live.add(key)
            full = sender.frames_in_flight >= sender.inflight_cap
            if full and flat:
                rounds = self._lane_full.get(key, 0) + 1
            else:
                rounds = 0
                self._lane_stuck.discard(key)
            self._lane_full[key] = rounds
            if rounds >= _STALL_ROUNDS and key not in self._lane_stuck:
                self._lane_stuck.add(key)
                self.emit(KIND_STUCK_LANE, None,
                          f"window toward {dest} full "
                          f"({sender.frames_in_flight}/"
                          f"{sender.inflight_cap} frames) for "
                          f"{rounds * self.interval_s:.1f}s with the "
                          f"commit waterline flat at {commits}")
        for key in list(self._lane_full):
            if key not in live:
                self._lane_full.pop(key, None)
        self._lane_stuck &= live
