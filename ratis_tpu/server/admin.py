"""Server-side admin operations: membership change and leadership transfer.

Capability parity with the reference's reconfiguration pipeline
(RaftServerImpl.setConfigurationAsync:1322, LeaderStateImpl
startSetConfiguration/checkStaging:828/applyOldNewConf:586, joint consensus
per RaftConfigurationImpl) and TransferLeadership
(ratis-server/.../impl/TransferLeadership.java:47).

Flow of a setConfiguration on the leader:
1. validate (leader, no conf change in flight, mode precondition);
2. STAGE: brand-new peers get log appenders *before* entering the conf
   (BootStrapProgress); wait until each is within the staging catch-up gap
   of the leader's last index;
3. append the JOINT entry (new conf + old conf) — quorum checks now require
   majorities in BOTH confs (the engine gets two masks);
4. when the joint entry is APPLIED, the leader appends the stable new-conf
   entry (reference appends it on commit of the old-new entry);
5. when the stable entry is applied, the pending request completes; a leader
   that is not in the new conf steps down (reference yields leadership).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Optional

from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.protocol.admin import (SetConfigurationArguments,
                                      SetConfigurationMode,
                                      TransferLeadershipArguments)
from ratis_tpu.protocol.exceptions import (LeaderSteppingDownException,
                                           RaftException,
                                           ReconfigurationInProgressException,
                                           TransferLeadershipException)
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest
from ratis_tpu.server.config import PeerConfiguration, RaftConfiguration

LOG = logging.getLogger(__name__)


@dataclasses.dataclass
class PendingReconf:
    """One in-flight setConfiguration (single-flight per group)."""

    joint_index: int = -1
    final_index: int = -1
    future: asyncio.Future = dataclasses.field(
        default_factory=lambda: asyncio.get_running_loop().create_future())

    def __post_init__(self):
        # The waiter may have timed out before a late failure is recorded;
        # retrieve the exception so the loop never logs it as unhandled.
        self.future.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None)


def _merge_new_conf(conf: RaftConfiguration,
                    args: SetConfigurationArguments
                    ) -> tuple[tuple[RaftPeer, ...], tuple[RaftPeer, ...]]:
    """Compute (voting, listeners) of the requested new conf per mode."""
    cur_v = {p.id: p for p in conf.conf.peers}
    cur_l = {p.id: p for p in conf.conf.listeners}
    if args.mode in (SetConfigurationMode.SET_UNCONDITIONALLY,
                     SetConfigurationMode.COMPARE_AND_SET):
        if args.mode == SetConfigurationMode.COMPARE_AND_SET:
            expected = {p.id for p in args.current_peers}
            if expected != set(cur_v):
                raise RaftException(
                    f"COMPARE_AND_SET precondition failed: current voting "
                    f"members {sorted(str(i) for i in cur_v)} != expected "
                    f"{sorted(str(i) for i in expected)}")
        return tuple(args.peers), tuple(args.listeners)
    if args.mode == SetConfigurationMode.ADD:
        for p in args.peers:
            cur_v[p.id] = p
            cur_l.pop(p.id, None)
        for p in args.listeners:
            cur_l[p.id] = p
            cur_v.pop(p.id, None)
        return tuple(cur_v.values()), tuple(cur_l.values())
    if args.mode == SetConfigurationMode.REMOVE:
        for p in (*args.peers, *args.listeners):
            cur_v.pop(p.id, None)
            cur_l.pop(p.id, None)
        return tuple(cur_v.values()), tuple(cur_l.values())
    raise RaftException(f"unknown mode {args.mode}")


def _same_membership(conf: RaftConfiguration, voting, listeners) -> bool:
    return (conf.is_stable()
            and set(conf.conf.peers) == set(voting)
            and set(conf.conf.listeners) == set(listeners))


async def set_configuration(div, req: RaftClientRequest) -> RaftClientReply:
    """The leader-side reconfiguration driver (see module docstring)."""
    err = div._check_leader(req)
    if err is not None:
        return err
    try:
        args = SetConfigurationArguments.from_payload(req.message.content)
    except Exception as e:
        return RaftClientReply.failure_reply(
            req, RaftException(f"bad setConfiguration payload: {e}"))

    state = div.state
    conf = state.configuration
    if div.pending_reconf is not None or conf.is_transitional():
        return RaftClientReply.failure_reply(
            req, ReconfigurationInProgressException(
                f"{div.member_id}: a configuration change is in progress"))
    try:
        voting, listeners = _merge_new_conf(conf, args)
    except RaftException as e:
        return RaftClientReply.failure_reply(req, e)
    if not voting:
        return RaftClientReply.failure_reply(
            req, RaftException("new configuration has no voting member"))
    if _same_membership(conf, voting, listeners):
        return RaftClientReply.success_reply(req, log_index=conf.log_index)

    pending = PendingReconf()
    div.pending_reconf = pending
    staged: list[RaftPeer] = []
    try:
        # -- stage brand-new members (BootStrapProgress) -------------------
        known = {p.id for p in conf.all_peers()}
        new_members = [p for p in (*voting, *listeners) if p.id not in known]
        for p in new_members:
            div.add_peer_for_staging(p)
            staged.append(p)
        if new_members:
            await _wait_caught_up(div, new_members, req.timeout_ms / 1000.0)

        if not div.is_leader() or div.leader_ctx is None:
            raise RaftException("lost leadership during staging")

        # -- append the joint entry ---------------------------------------
        log = state.log
        index = log.next_index
        joint = RaftConfiguration(
            PeerConfiguration(tuple(voting), tuple(listeners)),
            old_conf=conf.conf, log_index=index)
        pending.joint_index = index
        entry = joint.to_entry(state.current_term, index)
        await log.append_entry(entry)
        state.apply_log_entry_configuration(entry)
        div.on_configuration_changed()
        div._engine_update_flush()
        div.leader_ctx.notify_appenders()

        # -- wait for the stable entry to be applied (set by the apply-loop
        #    hook, Division._on_conf_entry_applied) ------------------------
        timeout_s = max(req.timeout_ms / 1000.0, 1.0)
        reply_index = await asyncio.wait_for(
            asyncio.shield(pending.future), timeout_s)
        return RaftClientReply.success_reply(req, log_index=reply_index)
    except asyncio.TimeoutError:
        return RaftClientReply.failure_reply(
            req, RaftException("setConfiguration timed out"))
    except RaftException as e:
        # failed before the joint entry: roll back staged appenders
        if pending.joint_index < 0:
            for p in staged:
                await div.remove_staged_peer(p.id)
        return RaftClientReply.failure_reply(req, e)
    finally:
        if div.pending_reconf is pending:
            div.pending_reconf = None


async def _wait_caught_up(div, peers: list[RaftPeer], timeout_s: float) -> None:
    """Staging gate: every new peer within the catch-up gap of the leader's
    last index (LeaderStateImpl.checkStaging:828)."""
    gap = div.server.properties.get_int(
        RaftServerConfigKeys.STAGING_CATCHUP_GAP_KEY,
        RaftServerConfigKeys.STAGING_CATCHUP_GAP_DEFAULT)
    deadline = asyncio.get_running_loop().time() + max(timeout_s, 1.0)
    while True:
        if not div.is_leader() or div.leader_ctx is None:
            raise RaftException("lost leadership during staging")
        last = div.state.log.next_index - 1
        ok = True
        for p in peers:
            f = div.leader_ctx.followers.get(p.id)
            if f is None or f.match_index < last - gap:
                ok = False
                break
        if ok:
            return
        if asyncio.get_running_loop().time() >= deadline:
            raise RaftException(
                f"staging timeout: new peers not caught up within {timeout_s}s")
        await asyncio.sleep(0.02)


async def transfer_leadership(div, req: RaftClientRequest) -> RaftClientReply:
    """Leader side of transfer: pick the target, wait for it to match our
    log, send StartLeaderElection, await the handover
    (TransferLeadership.java:47; Result types :84-97)."""
    err = div._check_leader(req)
    if err is not None:
        return err
    if div.hibernating:
        # a hibernated leader sends no heartbeats and its followers hold
        # no armed election timers — the handover below (catch-up wait +
        # StartLeaderElection) would stall against sleeping appenders, so
        # wake the group before transferring
        div.wake_from_hibernation("transfer-leadership")
    div.election_metrics.transfer_count.inc()
    try:
        args = TransferLeadershipArguments.from_payload(req.message.content)
    except Exception as e:
        return RaftClientReply.failure_reply(
            req, RaftException(f"bad transferLeadership payload: {e}"))

    state = div.state
    conf = state.configuration
    if args.new_leader:
        from ratis_tpu.protocol.ids import RaftPeerId
        target_id = RaftPeerId.value_of(args.new_leader)
        target = conf.get_peer(target_id)
        if target is None or target.is_listener() \
                or not conf.contains_voting(target_id):
            return RaftClientReply.failure_reply(
                req, TransferLeadershipException(
                    f"{args.new_leader} is not a voting member of {conf}"))
    else:
        # No explicit target: yield to the highest-priority peer
        # (reference checkPeersForYieldingLeader:1058; the loop below waits
        # for it to catch up, unlike the auto-yield which requires it).
        candidates = div.higher_priority_peers()
        if not candidates:
            return RaftClientReply.failure_reply(
                req, TransferLeadershipException(
                    "no higher-priority peer to yield to"))
        target = candidates[0]
        target_id = target.id

    timeout_s = max(args.timeout_ms / 1000.0, 0.2)
    deadline = asyncio.get_running_loop().time() + timeout_s
    div.stepping_down = True
    try:
        # 1. wait for the target to be fully caught up (match == our last);
        # 2. fire the forced election on it (re-firing if it loses a round);
        # 3. succeed only once the TARGET is the known leader (reference
        #    TransferLeadership completes on the matching leader event).
        last_sent = -1.0
        while asyncio.get_running_loop().time() < deadline:
            if not div.is_leader():
                if div.state.leader_id == target_id:
                    return RaftClientReply.success_reply(req)
                await asyncio.sleep(0.02)  # some other peer won; keep waiting
                continue
            ctx = div.leader_ctx
            f = ctx.followers.get(target_id) if ctx is not None else None
            last = state.log.next_index - 1
            now = asyncio.get_running_loop().time()
            if f is not None and f.match_index >= last \
                    and now - last_sent > 0.3:
                last_sent = now
                await div._send_start_leader_election(target_id)
            await asyncio.sleep(0.02)
        return RaftClientReply.failure_reply(
            req, TransferLeadershipException(
                f"transfer to {target_id} timed out after {timeout_s}s "
                f"(leader now {div.state.leader_id})"))
    finally:
        div.stepping_down = False
