"""In-memory RaftLog (volatile), for tests and memory-mode groups.

Capability parity with the reference MemoryRaftLog
(ratis-server/.../raftlog/memory/MemoryRaftLog.java): a plain entry list,
immediately 'flushed'.
"""

from __future__ import annotations

from typing import Optional

from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, TermIndex
from ratis_tpu.server.log.base import RaftLog


class MemoryRaftLog(RaftLog):
    def __init__(self, name: str = "memlog"):
        super().__init__(name)
        self._start = 0
        self._entries: list[LogEntry] = []
        # TermIndex of the entry just below start (snapshot boundary)
        self._below_start: Optional[TermIndex] = None

    async def open(self, last_index_on_snapshot: int = INVALID_LOG_INDEX) -> None:
        await super().open(last_index_on_snapshot)
        if last_index_on_snapshot != INVALID_LOG_INDEX and not self._entries:
            self._start = last_index_on_snapshot + 1

    @property
    def start_index(self) -> int:
        return self._start

    @property
    def next_index(self) -> int:
        # O(1) without TermIndex allocation: this is the single hottest log
        # accessor (appender fills, append handlers, bulk heartbeats)
        if self._entries:
            return self._start + len(self._entries)
        if self._below_start is not None:
            return self._below_start.index + 1
        return max(self._start, 0)

    @property
    def flush_index(self) -> int:
        return self.next_index - 1

    def get_last_entry_term_index(self) -> Optional[TermIndex]:
        if self._entries:
            return self._entries[-1].term_index()
        return self._below_start

    def get(self, index: int) -> Optional[LogEntry]:
        i = index - self._start
        if 0 <= i < len(self._entries):
            return self._entries[i]
        return None

    def get_term_index(self, index: int) -> Optional[TermIndex]:
        e = self.get(index)
        if e is not None:
            return e.term_index()
        if self._below_start is not None and index == self._below_start.index:
            return self._below_start
        return None

    async def append_entry(self, entry: LogEntry, wait_flush: bool = True) -> int:
        expected = self.next_index
        if entry.index != expected:
            raise ValueError(f"{self.name}: appending index {entry.index}, "
                             f"expected {expected}")
        self._entries.append(entry)
        return entry.index

    async def truncate(self, index: int) -> None:
        keep = max(0, index - self._start)
        del self._entries[keep:]

    async def purge(self, index: int) -> int:
        if index < self._start:
            return self._start - 1
        ti = self.get_term_index(index)
        drop = min(index - self._start + 1, len(self._entries))
        if drop > 0:
            del self._entries[:drop]
            self._start = index + 1
            self._below_start = ti
        return self._start - 1

    def set_snapshot_boundary(self, ti: TermIndex) -> None:
        """After installing a snapshot: log restarts above it."""
        self._entries.clear()
        self._start = ti.index + 1
        self._below_start = ti
