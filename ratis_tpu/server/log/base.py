"""RaftLog API and shared base behavior.

Capability parity with the reference RaftLog SPI
(ratis-server-api/.../server/raftlog/RaftLog.java:38 — commit tracking,
updateCommitIndex:114, purge:132) and RaftLogBase
(ratis-server/.../raftlog/RaftLogBase.java — append validation, the
truncate-and-append conflict resolution used by followers, open/close).

asyncio-native: ``append_entry`` returns once the entry is durable (flushed);
``flush_index`` feeds the leader's own slot in the batched commit kernel.
"""

from __future__ import annotations

import asyncio
from typing import Iterable, Optional, Sequence

from ratis_tpu.protocol.exceptions import LogCorruptedException, RaftException
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, TermIndex

LEAST_VALID_LOG_INDEX = 0


class RaftLog:
    """Abstract log of one division."""

    def __init__(self, name: str):
        self.name = name
        self._commit_index = INVALID_LOG_INDEX
        self._purge_index = INVALID_LOG_INDEX
        self._open = False
        # Flush observers (set by the division): invoked when flush_index
        # advances asynchronously / when a write fails.  Durable logs call
        # these from the worker's completion path; the in-memory log flushes
        # synchronously inside append so it never needs them.
        self._flush_cb = None
        self._flush_err_cb = None

    def set_flush_callbacks(self, on_flush, on_error) -> None:
        """on_flush(flush_index) fires after flush_index advances without the
        appender having awaited it (the decoupled leader path,
        reference SegmentedRaftLogWorker.java:302,368); on_error(exc) fires
        when the backing write fails (StateMachine.notifyLogFailed)."""
        self._flush_cb = on_flush
        self._flush_err_cb = on_error

    # -- open/close ----------------------------------------------------------

    async def open(self, last_index_on_snapshot: int = INVALID_LOG_INDEX) -> None:
        self._open = True

    async def close(self) -> None:
        self._open = False

    @property
    def is_open(self) -> bool:
        return self._open

    # -- indices -------------------------------------------------------------

    @property
    def commit_index(self) -> int:
        return self._commit_index

    def get_last_committed_index(self) -> int:
        return self._commit_index

    def update_commit_index(self, majority_index: int, current_term: int,
                            is_leader: bool) -> bool:
        """Advance commitIndex monotonically (RaftLog.updateCommitIndex:114).
        Leader-side term gating already happened in the quorum kernel; the
        follower side passes the leader's commit directly."""
        if majority_index <= self._commit_index:
            return False
        if is_leader:
            ti = self.get_term_index(majority_index)
            if ti is None or ti.term != current_term:
                return False
        self._commit_index = majority_index
        return True

    @property
    def start_index(self) -> int:
        raise NotImplementedError

    @property
    def next_index(self) -> int:
        ti = self.get_last_entry_term_index()
        return (ti.index + 1) if ti is not None else max(self.start_index, 0)

    @property
    def flush_index(self) -> int:
        raise NotImplementedError

    @property
    def failed(self) -> bool:
        """True once the log has latched dead on an IO failure: a node whose
        log cannot accept writes must not campaign or lead."""
        return False

    def get_last_entry_term_index(self) -> Optional[TermIndex]:
        raise NotImplementedError

    def get_term_index(self, index: int) -> Optional[TermIndex]:
        e = self.get(index)
        return e.term_index() if e is not None else None

    def get(self, index: int) -> Optional[LogEntry]:
        raise NotImplementedError

    def get_entries(self, start: int, end: int,
                    max_bytes: int = 1 << 62) -> list[LogEntry]:
        """Entries in [start, end) bounded by total serialized bytes — the
        appender batch builder (LogAppenderBase.newAppendEntriesRequest:223).
        Always returns at least one entry when available."""
        out: list[LogEntry] = []
        total = 0
        for i in range(start, min(end, self.next_index)):
            if out and not self.is_resident(i):
                # batch crossed into an evicted segment: stop here rather
                # than fault multi-MB of entries in synchronously; the
                # caller's next round prefaults off-loop
                break
            e = self.get(i)
            if e is None:
                break
            total += e.serialized_size()
            if out and total > max_bytes:
                break
            out.append(e)
        return out

    # -- append --------------------------------------------------------------

    async def append_entry(self, entry: LogEntry, wait_flush: bool = True) -> int:
        """Append one entry.  With ``wait_flush`` (follower path / default)
        the coroutine resolves only once the entry is durable — a follower's
        append reply must mean "on disk" (matchIndex == durable).  With
        ``wait_flush=False`` (leader hot path) it returns after the in-memory
        append: the write is queued, flush_index advances when the shared
        worker fsyncs, and the registered flush callback wakes the engine —
        the leader's commit math consumes flush_index, so correctness is
        preserved while the fsync overlaps follower RPCs (reference decouples
        identically: SegmentedRaftLog.appendEntryImpl:392 queues, flushIndex
        advances asynchronously)."""
        raise NotImplementedError

    async def append_entries_follower(self, entries: Sequence[LogEntry]) -> int:
        """Follower path: skip already-present matching entries, truncate at
        the first term conflict, then append the rest — the reference's
        truncate-and-append resolution (SegmentedRaftLog.appendEntryImpl:392,
        truncateImpl:363 and RaftLogBase.appendImpl).  Returns the new last
        index.  Raises LogCorruptedException when an existing committed entry
        conflicts."""
        if not entries:
            return self.next_index - 1
        to_append: list[LogEntry] = []
        truncate_at: Optional[int] = None
        for e in entries:
            if e.index < self.start_index:
                # Below our purge/snapshot boundary: already covered by the
                # installed snapshot (a leader rewound past our start after
                # a connection loss resends them) — skip, never re-append.
                continue
            existing = self.get_term_index(e.index)
            if existing is None:
                to_append.append(e)
            elif existing.term != e.term:
                if e.index <= self._commit_index:
                    raise LogCorruptedException(
                        f"{self.name}: conflict at committed index {e.index}: "
                        f"existing {existing}, new {e.term_index()}")
                truncate_at = e.index if truncate_at is None else min(truncate_at, e.index)
                to_append.append(e)
            # else: already have it; skip
        if truncate_at is not None:
            await self.truncate(truncate_at)
        # Queue the whole batch, await durability once: the shared worker
        # fsyncs in submission order, so the last entry's flush implies the
        # rest are on disk — one fsync per batch instead of one per entry
        # (the reference's LogWorker coalesces identically).
        for e in to_append[:-1]:
            await self.append_entry(e, wait_flush=False)
        if to_append:
            await self.append_entry(to_append[-1])
        return self.next_index - 1

    async def truncate(self, index: int) -> None:
        """Remove entries >= index."""
        raise NotImplementedError

    async def purge(self, index: int) -> int:
        """Drop entries <= index (snapshot-covered); returns new start-1."""
        raise NotImplementedError

    def evict_cache(self, applied_index: int) -> int:
        """Release entry memory no longer needed by the applier (the
        segmented log overrides this; volatile logs have nothing to evict)."""
        return 0

    def is_resident(self, index: int) -> bool:
        """False when reading ``index`` would block on a file fault (evicted
        segment); async hot paths prefault() off-loop first."""
        return True

    def prefault(self, index: int) -> None:
        """Blocking: fault the segment covering ``index`` into memory.
        No-op for fully-resident logs."""

    def term_at_or_before(self, index: int) -> Optional[TermIndex]:
        """TermIndex for a previous-entry check; None if purged away."""
        return self.get_term_index(index)

    def set_snapshot_boundary(self, ti: TermIndex) -> None:
        """Restart the log just above an installed/restored snapshot."""
        raise NotImplementedError
