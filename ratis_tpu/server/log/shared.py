"""Shared multi-group segmented log: one per-shard segment sequence.

Per-group durability (segmented.py) gives every division its own segment
files, so one replication sweep over N groups costs N buffered writes and
— because the shared LogWorker fsyncs once per *distinct file* per drain —
N fsyncs.  At 1024 groups the mixed filestore rung is syscall-bound, not
hardware-bound (ROADMAP item 3).

This store interleaves ALL divisions pinned to one loop shard into a
single sequence of append-only segment files.  Every record carries its
owning group and group-local index, so a sweep's appends from any number
of groups land in ONE file: the per-device LogWorker issues one buffered
write + one fsync per drain regardless of group count (fsyncs/commit
~1/groups instead of ~1).

Layout (under the peer's storage root, sibling of the per-group dirs —
``scan_group_dirs`` skips it because the name is not a group uuid)::

    <root>/_sharedlog/shard-<k>/
        shared_<n>              sealed segments, n monotonic
        shared_inprogress_<n>   the open segment (at most one)

Record format — the segmented store's CRC frame with a shared header::

    file    := MAGIC record*
    record  := u32_le payload_len | u32_le crc32(payload) | payload
    payload := group_id[16] | group_index i64 | term i64 | rtype u8 | body

    rtype 0 ENTRY      body = LogEntry msgpack (sm-data excluded)
    rtype 1 TOMBSTONE  logical truncate: group drops entries >= group_index
    rtype 2 PURGE      group drops entries <= group_index (term records the
                       boundary so recovery can restore the below-start
                       TermIndex after a full purge)

A follower rewind (the windowed-rewind path) therefore never rewrites
shared bytes: truncate appends a tombstone and drops in-memory tail state;
the dead records stay on disk until compaction.  Recovery rebuilds every
group's index in ONE forward scan of the shard's segments, replaying
records in file order: an entry at an already-held index implies
truncate-then-append (the follower conflict rule), tombstones and purges
apply as above, and a torn tail of the open segment is truncated away.

Each division's :class:`SharedGroupLog` keeps a dense in-memory index
(term + (segment, offset, len) per entry) serving the RaftLog read/term/
truncate API unchanged; entry payloads are cached until applied+flushed
and re-read from the shard file via ``os.pread`` afterwards (record-sized
reads, no whole-segment faulting, thread-safe for off-loop prefetch).

Compaction: tombstones/purges/overwrites mark the victim records' bytes
dead per segment.  When a sealed segment's dead ratio crosses the
configured threshold it is rewritten in place (tmp + rename) keeping live
entries and all control records — dropping a tombstone would let the
stale entries it killed in an *earlier* segment resurrect on replay, so
control records (a few dozen bytes each) are retained until their segment
retires entirely.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pathlib
import re
import struct
from typing import Optional

LOG = logging.getLogger(__name__)

from ratis_tpu.protocol.exceptions import (ChecksumException,
                                           RaftLogIOException)
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, TermIndex
from ratis_tpu.server.log.base import RaftLog
from ratis_tpu.server.log.segmented import (MAGIC, _REC_HDR, LogWorker,
                                            encode_record, read_records)

_SH_HDR = struct.Struct("<16sqqB")

REC_ENTRY = 0
REC_TOMBSTONE = 1
REC_PURGE = 2

_SEALED_RE = re.compile(r"^shared_(\d+)$")
_OPEN_RE = re.compile(r"^shared_inprogress_(\d+)$")

SHARED_DIR = "_sharedlog"


def shard_dir(storage_root: "str | pathlib.Path", shard: int) -> pathlib.Path:
    return pathlib.Path(storage_root) / SHARED_DIR / f"shard-{shard}"


def encode_shared(gid: bytes, index: int, term: int, rtype: int,
                  body: bytes = b"") -> bytes:
    return encode_record(_SH_HDR.pack(gid, index, term, rtype) + body)


def decode_shared(payload: bytes) -> tuple[bytes, int, int, int, bytes]:
    gid, index, term, rtype = _SH_HDR.unpack_from(payload, 0)
    return gid, index, term, rtype, payload[_SH_HDR.size:]


class _GroupState:
    """Dense per-group index: term + file location of each entry from
    ``first``.  Entry payloads live in the owning SharedGroupLog's cache."""

    __slots__ = ("first", "terms", "locs", "below_start")

    def __init__(self) -> None:
        self.first = 0
        self.terms: list[int] = []
        # (segment_number, record_offset, record_len) per entry
        self.locs: list[tuple[int, int, int]] = []
        self.below_start: Optional[TermIndex] = None

    @property
    def count(self) -> int:
        return len(self.terms)

    @property
    def last(self) -> int:
        return self.first + len(self.terms) - 1


class _ScanState:
    """Boot-scan working state: index -> (term, loc), hole-tolerant.

    Compaction can remove a dead record before the control record that
    killed it appears in scan order, so mid-scan the recovered index may
    have transient holes; they must all be closed by the time the stream
    ends (see ``SharedLogStore._finalize_group``)."""

    __slots__ = ("entries", "below_start")

    def __init__(self) -> None:
        self.entries: dict[int, tuple[int, tuple[int, int, int]]] = {}
        self.below_start: Optional[TermIndex] = None


class SharedLogStore:
    """One interleaved segment sequence per (server, loop shard).

    All file appends funnel through the shard's LogWorker into the single
    open segment, so one worker drain = one buffered write + one fsync for
    every division on the shard.  Divisions acquire/release the store; the
    first acquire runs the recovery scan, the last release drains and
    closes.  All mutating methods run on the shard's event loop (every
    division of a shard lives there); only ``read_record`` is
    thread-safe for off-loop reads.
    """

    def __init__(self, directory: "str | pathlib.Path", worker: LogWorker,
                 segment_size_max: int = 32 << 20,
                 compaction_dead_ratio: float = 0.5,
                 name: str = "shared", on_final_release=None):
        self.dir = pathlib.Path(directory)
        self.worker = worker
        self.segment_size_max = segment_size_max
        self.compaction_dead_ratio = compaction_dead_ratio
        self.name = name
        # invoked once the last division releases and the store has closed
        # (the owning server drops its registry entry; a re-added group
        # then gets a FRESH store instead of this closed one)
        self._on_final_release = on_final_release
        self._opened = False
        self._refs = 0
        self._open_file = None
        self._open_path: Optional[pathlib.Path] = None
        self._open_seg = -1
        self._open_size = 0
        self._next_seg = 0
        self._sealed: dict[int, pathlib.Path] = {}
        self._sizes: dict[int, int] = {}      # sealed segment byte sizes
        self._dead: dict[int, int] = {}       # dead ENTRY bytes per segment
        self._sealing_seg = -1                # mid-seal: compaction keep-out
        self._recovered: dict[bytes, _GroupState] = {}
        self._groups: dict[bytes, "SharedGroupLog"] = {}
        self._roll_lock = asyncio.Lock()
        self._compact_task: Optional[asyncio.Task] = None
        import threading
        self._fd_lock = threading.Lock()
        self._fds: dict[int, int] = {}
        from ratis_tpu.metrics import SharedLogMetrics
        self.metrics = SharedLogMetrics(name)
        self.metrics.add_store_gauges(
            lambda: self.total_bytes,
            lambda: len(self.worker._queue))

    # ------------------------------------------------------------ lifecycle

    def acquire(self, glog: "SharedGroupLog") -> None:
        self._refs += 1
        self._groups[glog.gid] = glog
        if not self._opened:
            self._opened = True
            self.worker.acquire()
            self._recover()

    async def release(self, glog: "SharedGroupLog") -> None:
        self._groups.pop(glog.gid, None)
        self._refs -= 1
        if self._refs > 0 or not self._opened:
            return
        self._opened = False
        if self._compact_task is not None:
            self._compact_task.cancel()
            try:
                await self._compact_task
            except BaseException:
                pass
            self._compact_task = None
        await self.worker.drain()
        if self._open_file is not None:
            self._open_file.close()
            self._open_file = None
        with self._fd_lock:
            for fd in self._fds.values():
                os.close(fd)
            self._fds.clear()
        await self.worker.release()
        self.metrics.unregister()
        if self._on_final_release is not None:
            self._on_final_release()

    @property
    def total_bytes(self) -> int:
        return sum(self._sizes.values()) + (
            self._open_size if self._open_file is not None else 0)

    # ------------------------------------------------------------- recovery

    def take_recovered(self, gid: bytes) -> _GroupState:
        return self._recovered.pop(gid, None) or _GroupState()

    def _recover(self) -> None:
        self.dir.mkdir(parents=True, exist_ok=True)
        found: list[tuple[int, bool, pathlib.Path]] = []
        for f in self.dir.iterdir():
            m = _SEALED_RE.match(f.name)
            if m:
                found.append((int(m.group(1)), False, f))
                continue
            m = _OPEN_RE.match(f.name)
            if m:
                found.append((int(m.group(1)), True, f))
        found.sort(key=lambda x: x[0])

        states: dict[bytes, _ScanState] = {}
        for pos, (n, was_open, path) in enumerate(found):
            payloads, good_len = read_records(path)
            file_size = path.stat().st_size
            if good_len < file_size:
                if not was_open:
                    raise ChecksumException(
                        f"{self.name}: corrupt sealed segment {path.name}",
                        good_len)
                with open(path, "r+b") as fh:
                    fh.truncate(good_len)
                file_size = good_len
            off = len(MAGIC)
            for p in payloads:
                self._replay(states, n, off, _REC_HDR.size + len(p), p)
                off += _REC_HDR.size + len(p)
            last = pos == len(found) - 1
            if was_open and last:
                self._open_path = path
                self._open_file = open(path, "ab")
                self._open_seg = n
                self._open_size = file_size
            else:
                if was_open:
                    # defensive: only the newest segment may stay open
                    sealed = path.with_name(f"shared_{n}")
                    os.replace(path, sealed)
                    path = sealed
                self._sealed[n] = path
                self._sizes[n] = file_size
            self._next_seg = max(self._next_seg, n + 1)

        for gid, rst in states.items():
            self._recovered[gid] = self._finalize_group(gid, rst)

    def _replay(self, states: dict, seg_n: int, off: int, rec_len: int,
                payload: bytes) -> None:
        """Hole-tolerant replay of one record into the scan-time state.

        Compaction removes dead ENTRY records but keeps every control
        record, so the scan can meet a forward gap whose missing middle is
        killed only by a LATER tombstone/purge/overwrite.  The scan state
        is therefore an index->(term, loc) dict that tolerates transient
        holes; ``_finalize_group`` demands contiguity once the whole
        stream has been applied.
        """
        gid, index, term, rtype, body = decode_shared(payload)
        st = states.get(gid)
        if st is None:
            st = states[gid] = _ScanState()
        entries = st.entries
        if rtype == REC_ENTRY:
            if st.below_start is not None and index <= st.below_start.index:
                self._dead[seg_n] = self._dead.get(seg_n, 0) + rec_len
                return
            # an append at index means nothing above it survived the write
            self._scan_kill_from(st, index)
            entries[index] = (term, (seg_n, off, rec_len))
        elif rtype == REC_TOMBSTONE:
            self._scan_kill_from(st, index)
        elif rtype == REC_PURGE:
            if st.below_start is not None and index <= st.below_start.index:
                return  # stale marker must not regress the boundary
            for i in list(entries):
                if i <= index:
                    _, (sn, _o, rl) = entries.pop(i)
                    self._dead[sn] = self._dead.get(sn, 0) + rl
            st.below_start = TermIndex(term, index)

    def _scan_kill_from(self, st: "_ScanState", index: int) -> None:
        """Drop scan-state entries >= index, charging their bytes dead."""
        for i in list(st.entries):
            if i >= index:
                _, (sn, _o, rl) = st.entries.pop(i)
                self._dead[sn] = self._dead.get(sn, 0) + rl

    def _finalize_group(self, gid: bytes, rst: "_ScanState") -> _GroupState:
        """Collapse the hole-tolerant scan state into the dense runtime
        index; a hole that survived the whole stream is real loss."""
        st = _GroupState()
        st.below_start = rst.below_start
        if not rst.entries:
            st.first = (rst.below_start.index + 1
                        if rst.below_start is not None else 0)
            return st
        lo, hi = min(rst.entries), max(rst.entries)
        if hi - lo + 1 != len(rst.entries):
            missing = next(i for i in range(lo, hi + 1)
                           if i not in rst.entries)
            raise ChecksumException(
                f"{self.name}: group {gid.hex()} lost record {missing} "
                f"(recovered range {lo}..{hi} has holes)", missing)
        st.first = lo
        for i in range(lo, hi + 1):
            term, loc = rst.entries[i]
            st.terms.append(term)
            st.locs.append(loc)
        return st

    def _kill_tail(self, st: _GroupState, index: int) -> None:
        """Drop st's entries >= index, charging their bytes dead."""
        i = max(0, index - st.first)
        for seg_n, _, rec_len in st.locs[i:]:
            self._dead[seg_n] = self._dead.get(seg_n, 0) + rec_len
        del st.terms[i:]
        del st.locs[i:]

    def _kill_head(self, st: _GroupState, index: int) -> None:
        """Drop st's entries <= index, charging their bytes dead."""
        if not st.count:
            return
        k = min(index - st.first + 1, st.count)
        if k <= 0:
            return
        for seg_n, _, rec_len in st.locs[:k]:
            self._dead[seg_n] = self._dead.get(seg_n, 0) + rec_len
        del st.terms[:k]
        del st.locs[:k]
        st.first += k

    # --------------------------------------------------------------- append

    def _ensure_open(self) -> None:
        if self._open_file is not None:
            return
        n = self._next_seg
        self._next_seg += 1
        path = self.dir / f"shared_inprogress_{n}"
        path.write_bytes(MAGIC)
        self._open_file = open(path, "ab")
        self._open_path = path
        self._open_seg = n
        self._open_size = len(MAGIC)

    async def _seal_open_segment(self) -> None:
        if self._open_file is None:
            return
        # Detach FIRST: submissions racing the drain below (e.g. another
        # group's snapshot-boundary marker) must open the next segment, not
        # queue a write the sealed file will never see.  Register the
        # segment for reads immediately (under its pre-rename path) and
        # keep compaction off it until its queued writes land.
        f, n, path = self._open_file, self._open_seg, self._open_path
        self._open_file = None
        self._open_path = None
        self._sealing_seg = n
        self._sealed[n] = path
        self._sizes[n] = self._open_size
        await self.worker.drain()
        f.close()
        sealed = path.with_name(f"shared_{n}")
        os.replace(path, sealed)
        self._sealed[n] = sealed
        self._sealing_seg = -1
        # the fd cache keyed the inode, which rename preserves — keep it

    def submit_record(self, gid: bytes, index: int, term: int, rtype: int,
                      body: bytes = b"") -> tuple[asyncio.Future, int, int, int]:
        """Queue one record on the open segment WITHOUT rolling — the
        synchronous path for control records from non-async callers; size
        overshoot is corrected by the next append_record."""
        self._ensure_open()
        rec = encode_shared(gid, index, term, rtype, body)
        off = self._open_size
        fut = self.worker.submit(self._open_file, rec)
        self._open_size += len(rec)
        return fut, self._open_seg, off, len(rec)

    async def append_record(self, gid: bytes, index: int, term: int,
                            rtype: int, body: bytes = b"") \
            -> tuple[asyncio.Future, int, int, int]:
        if self._open_file is not None \
                and self._open_size > self.segment_size_max:
            async with self._roll_lock:
                # re-check: a concurrent appender may have rolled already.
                # While someone holds this lock awaiting the drain, every
                # other group's append blocks HERE (the size check stays
                # true until the roll resets it), so no new write can be
                # queued against the file being sealed.
                if self._open_file is not None \
                        and self._open_size > self.segment_size_max:
                    await self._seal_open_segment()
        return self.submit_record(gid, index, term, rtype, body)

    # ---------------------------------------------------------------- reads

    def _fd(self, seg_n: int) -> int:
        with self._fd_lock:
            fd = self._fds.get(seg_n)
            if fd is not None:
                return fd
        path = self._sealed.get(seg_n)
        if path is None:
            if seg_n == self._open_seg and self._open_path is not None:
                path = self._open_path
            else:
                raise RaftLogIOException(
                    f"{self.name}: no segment {seg_n}")
        fd = os.open(path, os.O_RDONLY)
        with self._fd_lock:
            prior = self._fds.setdefault(seg_n, fd)
        if prior is not fd:
            os.close(fd)
            return prior
        return fd

    def _drop_fd(self, seg_n: int) -> None:
        with self._fd_lock:
            fd = self._fds.pop(seg_n, None)
        if fd is not None:
            os.close(fd)

    def read_record(self, seg_n: int, offset: int, rec_len: int) -> bytes:
        """Read one record's payload (thread-safe, pread-based)."""
        import zlib
        buf = os.pread(self._fd(seg_n), rec_len, offset)
        if len(buf) < _REC_HDR.size:
            raise ChecksumException(
                f"{self.name}: short read at {seg_n}:{offset}", offset)
        ln, crc = _REC_HDR.unpack_from(buf, 0)
        payload = buf[_REC_HDR.size:_REC_HDR.size + ln]
        if len(payload) != ln or zlib.crc32(payload) != crc:
            raise ChecksumException(
                f"{self.name}: corrupt record at {seg_n}:{offset}", offset)
        return payload

    # ----------------------------------------------------------- compaction

    def maybe_compact(self) -> None:
        """Kick background compaction of the worst sealed segment when its
        dead ratio crosses the threshold (one compaction at a time)."""
        if not self._opened:
            return
        if self._compact_task is not None and not self._compact_task.done():
            return
        target, worst = -1, self.compaction_dead_ratio
        for n, size in self._sizes.items():
            if size <= len(MAGIC) or n == self._sealing_seg:
                continue
            ratio = self._dead.get(n, 0) / size
            if ratio >= worst:
                target, worst = n, ratio
        if target < 0:
            return
        self._compact_task = asyncio.create_task(
            self._compact(target), name=f"shared-log-compact-{self.name}")

    async def _compact(self, seg_n: int) -> None:
        try:
            await self._compact_impl(seg_n)
        except asyncio.CancelledError:
            raise
        except Exception:
            LOG.exception("%s: compaction of segment %d failed",
                          self.name, seg_n)

    async def _compact_impl(self, seg_n: int) -> None:
        """Rewrite sealed segment ``seg_n`` keeping live entries and all
        control records.  Appends continue concurrently (they only touch
        the open segment); liveness is re-validated on the loop after the
        off-loop file read, and relocation double-checks each entry still
        points at its old offset before moving it."""
        path = self._sealed.get(seg_n)
        if path is None:
            return
        # the control records that killed this segment's dead entries may
        # still sit unflushed in the open segment; they must hit the disk
        # BEFORE the rewrite does, or a crash could persist the compaction
        # while losing its justification (an unrecoverable boot-scan hole)
        await self.worker.drain()
        data = await asyncio.to_thread(path.read_bytes)
        out = bytearray(MAGIC)
        moves: list[tuple[bytes, int, int, int, int]] = []
        off = len(MAGIC)
        while off + _REC_HDR.size <= len(data):
            ln, _ = _REC_HDR.unpack_from(data, off)
            end = off + _REC_HDR.size + ln
            if end > len(data):
                break
            rec = data[off:end]
            gid, index, _, rtype, _ = decode_shared(rec[_REC_HDR.size:])
            keep = True
            if rtype == REC_ENTRY:
                glog = self._groups.get(gid)
                keep = glog is None or glog.loc_at(index) == (seg_n, off)
            if keep:
                new_off = len(out)
                out += rec
                if rtype == REC_ENTRY:
                    moves.append((gid, index, off, new_off, len(rec)))
            off = end

        old_size = self._sizes.get(seg_n, len(data))
        if len(out) >= old_size:
            return  # nothing reclaimable (raced with resurrection)
        tmp = path.with_name(path.name + ".compact")

        def _write():
            with open(tmp, "wb") as f:
                f.write(out)
                f.flush()
                os.fsync(f.fileno())

        await asyncio.to_thread(_write)
        os.replace(tmp, path)
        self._drop_fd(seg_n)
        self._sizes[seg_n] = len(out)
        dead = 0
        for gid, index, old_off, new_off, rec_len in moves:
            glog = self._groups.get(gid)
            if glog is not None and glog.relocate(index, seg_n, old_off,
                                                  new_off, rec_len):
                continue
            dead += rec_len  # died while we were rewriting
        self._dead[seg_n] = dead
        self.metrics.compaction_count.inc()
        self.metrics.compaction_reclaimed.inc(old_size - len(out))


class SharedGroupLog(RaftLog):
    """One division's RaftLog view over a SharedLogStore.

    The full (term, location) index stays in memory; payloads are cached
    from append until applied+flushed, then served by record-sized preads.
    Truncate appends a durable tombstone (shared bytes are never
    rewritten); purge/snapshot-boundary append a durable purge marker so
    the one-pass boot scan reconstructs the same state.
    """

    def __init__(self, name: str, gid: bytes, store: SharedLogStore):
        super().__init__(name)
        self.store = store
        self.gid = gid
        self._st = _GroupState()
        self._entries: dict[int, LogEntry] = {}
        self._flush_index = INVALID_LOG_INDEX
        self._failed: Optional[Exception] = None
        from ratis_tpu.metrics import SegmentedRaftLogMetrics
        self.metrics = SegmentedRaftLogMetrics(name)

    @property
    def failed(self) -> bool:
        return self._failed is not None

    # ------------------------------------------------------------ open/close

    async def open(self, last_index_on_snapshot: int = INVALID_LOG_INDEX) -> None:
        await super().open(last_index_on_snapshot)
        self.store.acquire(self)
        self._st = self.store.take_recovered(self.gid)
        self._flush_index = self.next_index - 1

    async def close(self) -> None:
        await self.store.release(self)
        self.metrics.unregister()
        await super().close()

    # --------------------------------------------------------------- indices

    @property
    def start_index(self) -> int:
        st = self._st
        if st.count:
            return st.first
        if st.below_start is not None:
            return st.below_start.index + 1
        return 0

    @property
    def flush_index(self) -> int:
        return self._flush_index

    def get_last_entry_term_index(self) -> Optional[TermIndex]:
        st = self._st
        if st.count:
            return TermIndex(st.terms[-1], st.last)
        return st.below_start

    def get_term_index(self, index: int) -> Optional[TermIndex]:
        st = self._st
        i = index - st.first
        if st.count and 0 <= i < st.count:
            return TermIndex(st.terms[i], index)
        if st.below_start is not None and index == st.below_start.index:
            return st.below_start
        return None

    def loc_at(self, index: int) -> Optional[tuple[int, int]]:
        """(segment, offset) of a live entry, for compaction liveness."""
        st = self._st
        i = index - st.first
        if st.count and 0 <= i < st.count:
            seg_n, off, _ = st.locs[i]
            return seg_n, off
        return None

    def relocate(self, index: int, seg_n: int, old_off: int, new_off: int,
                 rec_len: int) -> bool:
        """Post-compaction pointer fixup; False if the entry died."""
        st = self._st
        i = index - st.first
        if st.count and 0 <= i < st.count \
                and st.locs[i] == (seg_n, old_off, rec_len):
            st.locs[i] = (seg_n, new_off, rec_len)
            return True
        return False

    # ----------------------------------------------------------------- reads

    def get(self, index: int) -> Optional[LogEntry]:
        st = self._st
        i = index - st.first
        if not st.count or not (0 <= i < st.count):
            return None
        e = self._entries.get(index)
        if e is None:
            self.metrics.cache_miss_count.inc()
            payload = self.store.read_record(*st.locs[i])
            _, ridx, _, rtype, body = decode_shared(payload)
            if ridx != index or rtype != REC_ENTRY:
                raise ChecksumException(
                    f"{self.name}: index {index} points at record "
                    f"({ridx}, rtype={rtype})", index)
            e = LogEntry.from_bytes(body)
        else:
            self.metrics.cache_hit_count.inc()
        return e

    # Record-sized preads make cold reads cheap enough to serve inline —
    # no whole-segment faulting, so the resident/prefault machinery the
    # segmented store needs (multi-MB synchronous loads) does not apply.
    def is_resident(self, index: int) -> bool:
        return True

    def prefault(self, index: int) -> None:
        pass

    def evict_cache(self, applied_index: int) -> int:
        """Drop payload cache at or below the applied frontier (the applier
        reads each entry once); only flushed entries are evictable — until
        the fsync their bytes may not be readable from the file."""
        limit = min(applied_index, self._flush_index)
        victims = [i for i in self._entries if i <= limit]
        for i in victims:
            del self._entries[i]
        if victims:
            self.metrics.cache_evict_count.inc(len(victims))
        return len(victims)

    # ---------------------------------------------------------------- append

    def _watch_control(self, fut: asyncio.Future) -> None:
        """Latch the failure latch if a control record's write fails."""
        def _done(f: asyncio.Future) -> None:
            if f.cancelled():
                return
            exc = f.exception()
            if exc is not None:
                first = self._failed is None
                self._failed = self._failed or exc
                if first and self._flush_err_cb is not None:
                    self._flush_err_cb(exc)
        fut.add_done_callback(_done)

    async def append_entry(self, entry: LogEntry, wait_flush: bool = True) -> int:
        with self.metrics.append_timer.time():
            return await self._append_entry_impl(entry, wait_flush)

    async def _append_entry_impl(self, entry: LogEntry,
                                 wait_flush: bool) -> int:
        if self._failed is not None:
            raise RaftLogIOException(
                f"{self.name}: log failed permanently") from self._failed
        expected = self.next_index
        if entry.index != expected:
            raise ValueError(f"{self.name}: appending index {entry.index}, "
                             f"expected {expected}")
        fut, seg_n, off, rec_len = await self.store.append_record(
            self.gid, entry.index, entry.term, REC_ENTRY,
            entry.to_bytes(include_sm_data=False))
        st = self._st
        if not st.count:
            st.first = entry.index
        st.terms.append(entry.term)
        st.locs.append((seg_n, off, rec_len))
        self._entries[entry.index] = entry
        index = entry.index

        # identical advance discipline to the per-group store: the worker
        # resolves a batch's futures in submit order, so flush_index stays
        # contiguous whether or not the caller awaits
        def _on_flush(f: asyncio.Future) -> None:
            if f.cancelled():
                return
            exc = f.exception()
            if exc is not None:
                first = self._failed is None
                self._failed = self._failed or exc
                if first and self._flush_err_cb is not None:
                    self._flush_err_cb(exc)
                return
            if self._failed is None and index > self._flush_index:
                self._flush_index = index
                if self._flush_cb is not None:
                    self._flush_cb(self._flush_index)

        fut.add_done_callback(_on_flush)
        if wait_flush:
            await fut
        return index

    # -------------------------------------------------------------- truncate

    async def truncate(self, index: int) -> None:
        """Logical truncate: durable tombstone + in-memory tail drop.  The
        shared file is append-only — a follower rewind never rewrites
        other groups' bytes."""
        self.metrics.truncate_count.inc()
        st = self._st
        if not st.count or index > st.last:
            return
        index = max(index, st.first)
        # settle in-flight appends first: a late-resolving future for a
        # truncated index must not advance flush_index past the new tail
        await self.store.worker.drain()
        fut, *_ = await self.store.append_record(
            self.gid, index, 0, REC_TOMBSTONE)
        self._watch_control(fut)
        i = index - st.first
        for j in range(i, st.count):
            self._entries.pop(st.first + j, None)
        self.store._kill_tail(st, index)
        self._flush_index = min(self._flush_index, self.next_index - 1)
        self.store.maybe_compact()
        await fut  # tombstone durable before the caller re-appends

    async def purge(self, index: int) -> int:
        """Exact-prefix purge behind a durable marker (the per-group store
        purges at segment granularity; here space comes back via
        compaction instead of file unlinks)."""
        ti = self.get_term_index(index)
        self.metrics.purge_count.inc()
        st = self._st
        if ti is None or not st.count or index < st.first:
            return self.start_index - 1
        fut, *_ = await self.store.append_record(
            self.gid, index, ti.term, REC_PURGE)
        self._watch_control(fut)
        limit = min(index, st.last)
        for j in range(st.first, limit + 1):
            self._entries.pop(j, None)
        self.store._kill_head(st, index)
        st.below_start = ti
        if not st.count:
            st.first = index + 1
        self.store.maybe_compact()
        return self.start_index - 1

    def set_snapshot_boundary(self, ti: TermIndex) -> None:
        """After snapshot install/restore: everything <= ti is covered.
        Durable via a purge marker (submitted, not awaited — callers are
        synchronous; a lost marker just replays covered entries)."""
        st = self._st
        if not st.count and st.below_start == ti:
            return  # boot-time re-assert of an already-recovered boundary
        fut, *_ = self.store.submit_record(
            self.gid, ti.index, ti.term, REC_PURGE)
        self._watch_control(fut)
        self._entries.clear()
        self.store._kill_tail(st, st.first)  # charge everything dead
        st.first = ti.index + 1
        st.below_start = ti
        self._flush_index = ti.index
        self.store.maybe_compact()
