"""Durable segmented Raft log with shared flush-batching worker.

Capability parity with the reference segmented log stack
(ratis-server/.../raftlog/segmented/SegmentedRaftLog.java:86,
SegmentedRaftLogWorker.java, LogSegment.java, SegmentedRaftLogFormat):

- segment files ``log_<start>-<end>`` (closed) / ``log_inprogress_<start>``
  (open) under ``current/`` (LogSegmentStartEnd.java:41-58);
- CRC-checked records, corrupt-tail truncation on recovery;
- a single I/O worker per *storage device* batching fsyncs across ALL
  divisions sharing that device (the reference runs one worker thread per
  division — SegmentedRaftLogWorker.java:302 — which is exactly the
  thread-per-group scaling wall this design removes, cf. SURVEY §7 step 5);
- flush_index advances only after fsync and feeds the leader's own slot in
  the batched commit kernel.

Record format (original to this implementation):
    file   := MAGIC record*
    record := u32_le payload_len | u32_le crc32(payload) | payload
    payload = LogEntry msgpack bytes
"""

from __future__ import annotations

import asyncio
import os
import pathlib
import re
import struct
import time
import zlib
from typing import Optional

from ratis_tpu.protocol.exceptions import (ChecksumException,
                                           RaftLogIOException)
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, TermIndex
from ratis_tpu.server.log.base import RaftLog

MAGIC = b"RTPULOG\x01"
_REC_HDR = struct.Struct("<II")

_CLOSED_RE = re.compile(r"^log_(\d+)-(\d+)$")
_OPEN_RE = re.compile(r"^log_inprogress_(\d+)$")


def encode_record(payload: bytes) -> bytes:
    return _REC_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def read_records(path: pathlib.Path) -> tuple[list[bytes], int]:
    """Read records; returns (payloads, good_byte_length).  Stops at the
    first corrupt/truncated record — recovery truncates the file there
    (reference SegmentedRaftLogReader corrupt-tail handling)."""
    data = path.read_bytes()
    if not data.startswith(MAGIC):
        return [], len(MAGIC) if not data else 0
    payloads = []
    off = len(MAGIC)
    while off + _REC_HDR.size <= len(data):
        ln, crc = _REC_HDR.unpack_from(data, off)
        start = off + _REC_HDR.size
        end = start + ln
        if end > len(data):
            break
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            break
        payloads.append(payload)
        off = end
    return payloads, off


class LogWorker:
    """One fsync-batching writer per storage device.

    Tasks are (file, bytes, future) appends; each drain writes every queued
    task then issues ONE fsync per distinct file, resolving all futures —
    group commit like the reference's flushIfNecessary/forceSyncNum
    (SegmentedRaftLogWorker.java:368) but across divisions.
    """

    _instances: dict[str, "LogWorker"] = {}

    def __init__(self, name: str = "default"):
        self.name = name
        self._queue: list[tuple[object, bytes, asyncio.Future]] = []
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._refs = 0
        # single metric source (reference log_worker catalog: flushTime/
        # flushCount/syncTime over the shared per-device worker)
        from ratis_tpu.metrics import LogWorkerMetrics
        self.registry_metrics = LogWorkerMetrics(f"device-{name}")
        self.registry_metrics.add_queue_gauges(lambda: len(self._queue))
        self.registry_metrics.add_sweep_gauge(lambda: self._sync_ewma)
        self._writes = self.registry_metrics.registry.counter("writeCount")
        self._batches = self.registry_metrics.registry.counter("batchCount")
        # decayed fsyncs-per-drain-sweep: ~1.0 on a shared log plane,
        # ~open-file-count with per-group segment files
        self._sync_ewma = 0.0

    @property
    def metrics(self) -> dict:
        """Snapshot view kept for tests/tools."""
        return {"flushes": self.registry_metrics.flush_count.count,
                "writes": self._writes.count,
                "batched": self._batches.count}

    @property
    def sync_count(self) -> int:
        """Cumulative fsyncs issued by this worker."""
        return self.registry_metrics.sync_count.count

    @classmethod
    def shared(cls, device_key: str) -> "LogWorker":
        w = cls._instances.get(device_key)
        if w is None:
            w = cls(device_key)
            cls._instances[device_key] = w
        return w

    def acquire(self) -> None:
        self._refs += 1
        if self._task is None:
            self._wake = asyncio.Event()
            self._task = asyncio.create_task(self._run(),
                                             name=f"log-worker-{self.name}")

    async def release(self) -> None:
        if self._refs <= 0:
            return  # tolerate close-without-open (failed startup cleanup)
        self._refs -= 1
        if self._refs <= 0 and self._task is not None:
            task, self._task = self._task, None
            self._wake.set()
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            self._instances.pop(self.name, None)
            self.registry_metrics.unregister()

    def submit(self, fileobj, data: bytes) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((fileobj, data, fut))
        if self._wake is not None:
            self._wake.set()
        return fut

    async def drain(self) -> None:
        """Wait until previously submitted writes are flushed."""
        if not self._queue:
            return
        fut = self._queue[-1][2]
        await asyncio.shield(fut)

    async def _run(self) -> None:
        from ratis_tpu.util import injection
        # worker-start injection point (reference
        # SegmentedRaftLogWorker.java:70 runs CodeInjectionForTesting at
        # the top of its run loop): lets the chaos suite stall a device's
        # whole log worker before it drains anything
        await injection.execute(injection.RUN_LOG_WORKER, self.name)
        while True:
            if not self._queue:
                self._wake.clear()
                await self._wake.wait()
            batch, self._queue = self._queue, []
            if not batch:
                continue
            self._writes.inc(len(batch))
            self._batches.inc()
            # per-flush-batch sync injection point (reference
            # RaftServerImpl.java:1620's LOG_SYNC): a registered delay
            # here is the slow-disk fault — every group sharing this
            # device pays it, exactly like a real degraded disk.  The
            # extra arg is the batch's distinct-file count, so a handler
            # can charge per FSYNC (per-group segments pay N, the shared
            # plane pays 1) rather than per sweep.
            files_n = len({id(fileobj) for fileobj, _, _ in batch})
            await injection.execute(injection.LOG_SYNC, self.name, None,
                                    files_n)

            def _do_io():
                files = []
                for fileobj, data, _ in batch:
                    fileobj.write(data)
                    if fileobj not in files:
                        files.append(fileobj)
                t_sync = time.perf_counter()
                for f in files:
                    f.flush()
                    os.fsync(f.fileno())
                self.registry_metrics.sync_timer.update(
                    time.perf_counter() - t_sync)
                self.registry_metrics.sync_count.inc(len(files))
                self._sync_ewma = (0.9 * self._sync_ewma + 0.1 * len(files)
                                   if self._sync_ewma else float(len(files)))

            try:
                with self.registry_metrics.flush_timer.time():
                    await asyncio.to_thread(_do_io)
                self.registry_metrics.flush_count.inc()
                for _, _, fut in batch:
                    if not fut.done():
                        fut.set_result(None)
            except Exception as e:
                for _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)


class _Segment:
    """One segment: its file, per-entry (term, offset) metadata, and — while
    cached — the decoded entries.

    Mirrors the reference LogSegment (LogSegment.java): the compact LogRecord
    list (term + file position per entry) always stays in memory so
    consistency checks (get_term_index / previous-entry validation) never
    touch disk, while the entry payloads can be evicted
    (SegmentedRaftLogCache.java evictCache) and read back through the file on
    demand for lagging followers."""

    def __init__(self, start: int, path: pathlib.Path, is_open: bool):
        self.start = start
        self.path = path
        self.is_open = is_open
        # None = evicted (payloads live only in the file)
        self.entries: Optional[list[LogEntry]] = []
        # always-resident metadata: term + byte offset of each record
        self.terms: list[int] = []
        self.offsets: list[int] = []
        self.size = len(MAGIC)

    def append(self, entry: LogEntry, offset: int, record_len: int) -> None:
        assert self.entries is not None, "append to evicted segment"
        self.entries.append(entry)
        self.terms.append(entry.term)
        self.offsets.append(offset)
        self.size = offset + record_len

    @property
    def count(self) -> int:
        return len(self.terms)

    @property
    def end(self) -> int:
        return self.start + len(self.terms) - 1

    @property
    def cached(self) -> bool:
        return self.entries is not None

    def evict(self) -> None:
        assert not self.is_open
        self.entries = None

    def term_at(self, index: int) -> Optional[int]:
        i = index - self.start
        if 0 <= i < len(self.terms):
            return self.terms[i]
        return None

    def get(self, index: int) -> Optional[LogEntry]:
        i = index - self.start
        if 0 <= i < len(self.terms) and self.entries is not None:
            return self.entries[i]
        return None

    def load(self) -> list[LogEntry]:
        """Read the whole segment back from disk (read-through miss)."""
        payloads, _ = read_records(self.path)
        return [LogEntry.from_bytes(p) for p in payloads]


class SegmentedRaftLog(RaftLog):
    def __init__(self, name: str, directory: pathlib.Path,
                 worker: Optional[LogWorker] = None,
                 segment_size_max: int = 8 << 20,
                 cache_segments_max: int = 6):
        super().__init__(name)
        self.dir = pathlib.Path(directory)
        self.worker = worker or LogWorker.shared(str(self.dir.anchor or "default"))
        self.segment_size_max = segment_size_max
        # Closed segments beyond this many keep only (term, offset) metadata
        # in RAM; payloads are re-read from the file on demand (reference
        # SegmentedRaftLogCache.java default 6 cached segments).
        self.cache_segments_max = cache_segments_max
        self._segments: list[_Segment] = []
        # read-through cache: seg.start -> entries, tiny LRU (a couple of
        # lagging followers scanning different segments shouldn't thrash).
        # Guarded by a threading lock: prefault() runs in to_thread workers
        # concurrently with event-loop readers.
        self._rt_cache: "dict[int, list[LogEntry]]" = {}
        self._rt_cache_max = 3
        self._rt_version = 0  # bumped on truncate/purge/snapshot invalidation
        import threading
        self._rt_lock = threading.Lock()
        self._open_file = None
        self._flush_index = INVALID_LOG_INDEX
        self._below_start: Optional[TermIndex] = None
        # Latched on the first failed write: flush_index must never advance
        # past a hole (a later successful fsync does NOT make earlier failed
        # bytes durable), and further appends are refused — the reference's
        # log worker terminates on IO failure the same way.
        self._failed: Optional[Exception] = None
        from ratis_tpu.metrics import SegmentedRaftLogMetrics
        self.metrics = SegmentedRaftLogMetrics(name)

    @property
    def failed(self) -> bool:
        return self._failed is not None

    # ------------------------------------------------------------- recovery

    async def open(self, last_index_on_snapshot: int = INVALID_LOG_INDEX) -> None:
        await super().open(last_index_on_snapshot)
        self.worker.acquire()
        self.dir.mkdir(parents=True, exist_ok=True)
        found: list[tuple[int, Optional[int], pathlib.Path]] = []
        for f in self.dir.iterdir():
            m = _CLOSED_RE.match(f.name)
            if m:
                found.append((int(m.group(1)), int(m.group(2)), f))
                continue
            m = _OPEN_RE.match(f.name)
            if m:
                found.append((int(m.group(1)), None, f))
        found.sort(key=lambda x: x[0])

        for start, end, path in found:
            seg = _Segment(start, path, end is None)
            payloads, good_len = read_records(path)
            file_size = path.stat().st_size
            if good_len < file_size:
                if end is not None:
                    raise ChecksumException(
                        f"{self.name}: corrupt closed segment {path.name}",
                        good_len)
                # corrupt tail of the open segment: truncate it away
                with open(path, "r+b") as fh:
                    fh.truncate(good_len)
            off = len(MAGIC)
            for p in payloads:
                e = LogEntry.from_bytes(p)
                seg.append(e, off, _REC_HDR.size + len(p))
                off += _REC_HDR.size + len(p)
            if seg.count or seg.is_open:
                self._segments.append(seg)

        # Only the last segment may be open; close others defensively.
        for seg in self._segments[:-1]:
            if seg.is_open:
                self._close_segment_file(seg)
        if self._segments and self._segments[-1].is_open:
            seg = self._segments[-1]
            self._open_file = open(seg.path, "ab")
        # NOTE: when the log is empty and a snapshot exists, the caller must
        # follow open() with set_snapshot_boundary(snapshot.term_index) — the
        # term is not recoverable from the index argument alone.
        self._flush_index = self.next_index - 1

    async def close(self) -> None:
        if self._open_file is not None:
            await self.worker.drain()
            self._open_file.close()
            self._open_file = None
        await self.worker.release()
        self.metrics.unregister()
        await super().close()

    def _close_segment_file(self, seg: _Segment) -> None:
        if not seg.count:
            seg.path.unlink(missing_ok=True)
            return
        new_path = seg.path.with_name(f"log_{seg.start}-{seg.end}")
        os.replace(seg.path, new_path)
        seg.path = new_path
        seg.is_open = False

    # ------------------------------------------------------------- indices

    @property
    def start_index(self) -> int:
        if self._segments:
            return self._segments[0].start
        if self._below_start is not None:
            return self._below_start.index + 1
        return 0

    @property
    def flush_index(self) -> int:
        return self._flush_index

    def get_last_entry_term_index(self) -> Optional[TermIndex]:
        for seg in reversed(self._segments):
            if seg.count:
                return TermIndex(seg.terms[-1], seg.end)
        return self._below_start

    def _fault_in(self, seg: _Segment) -> list[LogEntry]:
        with self._rt_lock:
            entries = self._rt_cache.get(seg.start)
            version = self._rt_version
        if entries is None:
            self.metrics.cache_miss_count.inc()
            entries = seg.load()  # file IO outside the lock
            with self._rt_lock:
                if self._rt_version == version:
                    # don't cache across an invalidation (a truncate may
                    # have rewritten the file while we were reading it)
                    self._rt_cache[seg.start] = entries
                    while len(self._rt_cache) > self._rt_cache_max:
                        self._rt_cache.pop(next(iter(self._rt_cache)))
        else:
            self.metrics.cache_hit_count.inc()
        return entries

    def _invalidate_rt_cache(self) -> None:
        with self._rt_lock:
            self._rt_version += 1
            self._rt_cache.clear()

    def _read_through(self, seg: _Segment, index: int) -> Optional[LogEntry]:
        """Serve an evicted segment from its file (one whole-segment read,
        held in a small LRU for the sequential scans a catching-up follower
        produces).  Synchronous: async hot paths should check is_resident()
        first and prefault() off-loop (LogAppender does)."""
        entries = self._fault_in(seg)
        i = index - seg.start
        if 0 <= i < len(entries):
            return entries[i]
        return None

    def _covering_segment(self, index: int) -> Optional[_Segment]:
        for seg in reversed(self._segments):
            if seg.start <= index:
                return seg if index <= seg.end else None
        return None

    def is_resident(self, index: int) -> bool:
        seg = self._covering_segment(index)
        if seg is None or seg.cached:
            return True
        # _rt_cache is mutated from prefault worker threads; the lock is
        # uncontended and keeps this membership check from racing an LRU
        # eviction into a synchronous whole-segment load on the event loop
        with self._rt_lock:
            return seg.start in self._rt_cache

    def prefault(self, index: int) -> None:
        """Blocking load of the segment covering ``index`` into the
        read-through cache; call via asyncio.to_thread from async paths."""
        seg = self._covering_segment(index)
        if seg is not None and not seg.cached:
            self._fault_in(seg)

    def get(self, index: int) -> Optional[LogEntry]:
        for seg in reversed(self._segments):
            if seg.start <= index:
                if index > seg.end:
                    return None
                if seg.cached:
                    return seg.get(index)
                return self._read_through(seg, index)
        return None

    def get_term_index(self, index: int) -> Optional[TermIndex]:
        # metadata-only: never faults an evicted segment in
        for seg in reversed(self._segments):
            if seg.start <= index:
                t = seg.term_at(index)
                return TermIndex(t, index) if t is not None else None
        if self._below_start is not None and index == self._below_start.index:
            return self._below_start
        return None

    # ------------------------------------------------------------- eviction

    @property
    def cached_segments(self) -> int:
        return sum(1 for s in self._segments if not s.is_open and s.cached)

    def evict_cache(self, applied_index: int) -> int:
        """Bound entry memory (reference SegmentedRaftLogCache.evictCache):
        keep at most cache_segments_max closed segments' payloads resident,
        evicting oldest-first but only below the applied frontier (the
        applier reads every entry exactly once; evicting ahead of it would
        thrash).  Lagging followers are served from disk via read-through.
        Returns the number of segments evicted."""
        # cheap guard: runs on every apply batch, almost always a no-op
        if len(self._segments) <= self.cache_segments_max + 1:
            return 0
        closed_cached = [s for s in self._segments
                         if not s.is_open and s.cached]
        excess = len(closed_cached) - self.cache_segments_max
        evicted = 0
        for seg in closed_cached:
            if evicted >= excess:
                break
            if seg.end <= applied_index:
                seg.evict()
                self.metrics.cache_evict_count.inc()
                evicted += 1
        return evicted

    # ------------------------------------------------------------- append

    def _ensure_open_segment(self, start: int) -> _Segment:
        if self._segments and self._segments[-1].is_open:
            return self._segments[-1]
        seg = _Segment(start, self.dir / f"log_inprogress_{start}", True)
        seg.path.write_bytes(MAGIC)
        self._segments.append(seg)
        self._open_file = open(seg.path, "ab")
        return seg

    async def _roll_segment(self) -> None:
        await self.worker.drain()
        seg = self._segments[-1]
        self._open_file.close()
        self._open_file = None
        self._close_segment_file(seg)

    async def append_entry(self, entry: LogEntry, wait_flush: bool = True) -> int:
        with self.metrics.append_timer.time():
            return await self._append_entry_impl(entry, wait_flush)

    async def _append_entry_impl(self, entry: LogEntry,
                                 wait_flush: bool) -> int:
        if self._failed is not None:
            raise RaftLogIOException(
                f"{self.name}: log failed permanently") from self._failed
        expected = self.next_index
        if entry.index != expected:
            raise ValueError(f"{self.name}: appending index {entry.index}, "
                             f"expected {expected}")
        seg = self._ensure_open_segment(entry.index)
        if seg.size > self.segment_size_max:
            await self._roll_segment()
            seg = self._ensure_open_segment(entry.index)

        payload = entry.to_bytes(include_sm_data=False)
        record = encode_record(payload)
        seg.append(entry, seg.size, len(record))
        fut = self.worker.submit(self._open_file, record)
        index = entry.index

        # flush_index advances from the worker's completion, in submit order
        # (the worker resolves a batch's futures in order, and done-callbacks
        # run before any awaiter resumes), so it stays contiguous whether or
        # not the caller awaits (SegmentedRaftLogWorker flushIfNecessary:368).
        def _on_flush(f: "asyncio.Future") -> None:
            if f.cancelled():
                return
            exc = f.exception()
            if exc is not None:
                first = self._failed is None
                self._failed = self._failed or exc
                if first and self._flush_err_cb is not None:
                    self._flush_err_cb(exc)
                return
            if self._failed is None and index > self._flush_index:
                self._flush_index = index
                if self._flush_cb is not None:
                    self._flush_cb(self._flush_index)

        fut.add_done_callback(_on_flush)
        if wait_flush:
            await fut
        return index

    # ------------------------------------------------------------ truncate

    async def truncate(self, index: int) -> None:
        self.metrics.truncate_count.inc()
        self._invalidate_rt_cache()
        await self.worker.drain()
        while self._segments and self._segments[-1].start >= index:
            seg = self._segments.pop()
            if seg.is_open and self._open_file is not None:
                self._open_file.close()
                self._open_file = None
            seg.path.unlink(missing_ok=True)
        if not self._segments:
            self._flush_index = min(self._flush_index, index - 1)
            return
        seg = self._segments[-1]
        if index <= seg.end:
            if not seg.cached:
                seg.entries = seg.load()  # truncation rewrites the tail
            keep = index - seg.start
            byte_len = seg.offsets[keep] if keep < len(seg.offsets) else seg.size
            if seg.is_open and self._open_file is not None:
                self._open_file.close()
                self._open_file = None
            del seg.entries[keep:]
            del seg.terms[keep:]
            del seg.offsets[keep:]
            with open(seg.path, "r+b") as fh:
                fh.truncate(byte_len)
            seg.size = byte_len
            if not seg.is_open:
                # reopen as inprogress for future appends
                new_path = seg.path.with_name(f"log_inprogress_{seg.start}")
                os.replace(seg.path, new_path)
                seg.path = new_path
                seg.is_open = True
            self._open_file = open(seg.path, "ab")
        self._flush_index = min(self._flush_index, self.next_index - 1)

    async def purge(self, index: int) -> int:
        """Drop whole segments with end <= index (snapshot-covered); the
        reference purges at segment granularity too (purgeImpl)."""
        ti = self.get_term_index(index)
        self.metrics.purge_count.inc()
        self._invalidate_rt_cache()
        # Roll the open segment first when the snapshot fully covers it, so
        # purge can reclaim it too (otherwise a single-open-segment log would
        # never shrink after snapshotting).
        if self._segments and self._segments[-1].is_open \
                and self._segments[-1].count \
                and self._segments[-1].end <= index:
            await self._roll_segment()
        dropped = False
        while self._segments and not self._segments[0].is_open \
                and self._segments[0].end <= index:
            seg = self._segments.pop(0)
            seg.path.unlink(missing_ok=True)
            dropped = True
        if dropped and ti is not None and (not self._segments
                                           or self._segments[0].start > index):
            self._below_start = ti
        return self.start_index - 1

    def set_snapshot_boundary(self, ti: TermIndex) -> None:
        """After snapshot install: discard the local log below/at ti."""
        self._invalidate_rt_cache()
        for seg in self._segments:
            seg.path.unlink(missing_ok=True)
        self._segments.clear()
        if self._open_file is not None:
            self._open_file.close()
            self._open_file = None
        self._below_start = ti
        self._flush_index = ti.index
