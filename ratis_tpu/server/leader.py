"""Leader-side machinery: pending requests, watch bookkeeping, log appenders.

Capability parity with the reference LeaderStateImpl + LogAppender
(ratis-server/.../impl/LeaderStateImpl.java:101, PendingRequests.java:51,
leader/LogAppenderBase.java:50, LogAppenderDefault.java:43): per-follower
replication drivers with batched AppendEntries and nextIndex backoff, a
pending-request registry completed on apply, and step-down draining.

Differences from the reference by design: there is no per-group
EventProcessor thread — commit advancement happens in the server-wide
QuorumEngine (ratis_tpu.engine) and calls back into the division.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ratis_tpu.metrics.hops import hop
from ratis_tpu.protocol.exceptions import (NotLeaderException,
                                           ResourceUnavailableException)
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.raftrpc import (AppendEntriesReply,
                                        AppendEntriesRequest, AppendResult,
                                        RaftRpcHeader)
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.server.replication import OutItem

LOG = logging.getLogger(__name__)


class PendingRequest:
    def __init__(self, index: int, request: RaftClientRequest):
        self.index = index
        self.request = request
        self.future: asyncio.Future = asyncio.get_running_loop().create_future()
        # Deferred-reply mode (commit fan-out collapse): a synchronous
        # completion callback replaces the per-request future wakeup chain
        # — the waterline fan-out invokes it inline and the reply lands in
        # the transport's per-connection batcher with no task resume.
        self._sink_cb = None

    def deliver_to(self, cb) -> None:
        """Register the deferred completion callback.  If the reply was
        already set (e.g. a step-down drain raced the append await), the
        callback fires immediately — exactly-once either way."""
        self._sink_cb = cb
        if self.future.done() and not self.future.cancelled():
            cb(self.future.result())

    def _resolve(self, reply: RaftClientReply) -> None:
        if self.future.done():
            return
        self.future.set_result(reply)
        cb = self._sink_cb
        if cb is not None:
            cb(reply)
        else:
            # legacy commit->reply path: this resolution wakes the parked
            # write-handler task — the per-request hop the waterline
            # fan-out removes (metric site, see metrics/hops.py)
            hop("reply_future")

    def set_reply(self, reply: RaftClientReply) -> None:
        self._resolve(reply)

    def fail(self, exception: Exception) -> None:
        self._resolve(RaftClientReply.failure_reply(self.request, exception))


class PendingRequests:
    """index -> in-flight client write, with byte/element permits
    (reference PendingRequests.java:51,100-110)."""

    def __init__(self, element_limit: int = 4096, byte_limit: int = 64 << 20,
                 mirror=None):
        self._map: dict[int, PendingRequest] = {}
        self._element_limit = element_limit
        self._byte_limit = byte_limit
        self._bytes = 0
        # depth mirror into the engine's pending_count[G] (lag ledger /
        # telemetry sampler read it array-wise instead of walking leaders)
        self._mirror = mirror

    def add(self, index: int, request: RaftClientRequest) -> PendingRequest:
        size = request.message.size()
        if (len(self._map) >= self._element_limit
                or (self._bytes + size) > self._byte_limit):
            raise ResourceUnavailableException(
                f"pending requests full: {len(self._map)} elements, "
                f"{self._bytes} bytes")
        p = PendingRequest(index, request)
        self._map[index] = p
        self._bytes += size
        if self._mirror is not None:
            self._mirror(len(self._map))
        return p

    def pop(self, index: int) -> Optional[PendingRequest]:
        p = self._map.pop(index, None)
        if p is not None:
            self._bytes -= p.request.message.size()
            if self._mirror is not None:
                self._mirror(len(self._map))
        return p

    def requests(self) -> list[RaftClientRequest]:
        return [p.request for p in self._map.values()]

    def drain_not_leader(self, exception: NotLeaderException) -> int:
        """Step-down: fail everything (PendingRequests.notifyNotLeader)."""
        n = len(self._map)
        for p in self._map.values():
            p.fail(exception)
        self._map.clear()
        self._bytes = 0
        if self._mirror is not None:
            self._mirror(0)
        return n

    def __len__(self) -> int:
        return len(self._map)


class FollowerInfo:
    """Leader's view of one follower (reference server-api leader/FollowerInfo)."""

    def __init__(self, peer_id: RaftPeerId, next_index: int):
        self.peer_id = peer_id
        self.next_index = next_index
        self.match_index = -1
        self.commit_index = -1  # piggybacked on append replies
        self.snapshot_in_progress = False
        self.attend_vote = True  # False for listeners
        self.last_rpc_response_s = time.monotonic()

    def update_match(self, match: int) -> bool:
        self.last_rpc_response_s = time.monotonic()
        if match > self.match_index:
            self.match_index = match
            return True
        return False

class LogAppender:
    """One leader->follower replication state machine with a pipelined send
    window, driven by the server-level PeerSender fabric.

    Mirrors the reference GrpcLogAppender (GrpcLogAppender.java:343-381):
    up to ``window_limit`` AppendEntries requests are in flight at once —
    ``follower.next_index`` is the optimistic *send* cursor, advanced when a
    batch is handed to the transport, while ``follower.match_index`` advances
    only on acks.  Unlike the reference there is NO daemon per (group,
    follower): the appender is passive state; the per-destination PeerSender
    (ratis_tpu.server.replication) calls :meth:`collect` to drain its window
    fills into shared multi-group envelopes and dispatches replies back via
    :meth:`on_send_reply`/:meth:`on_send_error`.  Per-group FIFO holds (see
    replication module docstring); reordered delivery at worst produces a
    spurious INCONSISTENCY -> window reset + resend, and match only ever
    advances from per-request-capped SUCCESS confirmations.  A dedicated
    heartbeat timer (reference's separate heartbeat channel,
    GrpcLogAppender.java:172) fires outside the window and is never queued
    behind a full pipeline.  On INCONSISTENCY or an RPC error the window
    resets: the epoch is bumped so in-flight completions from before the
    reset are ignored, and the send cursor rewinds
    (GrpcLogAppender.onError/resetClient:475-530).
    """

    def __init__(self, division, follower: FollowerInfo,
                 heartbeat_interval_s: float, buffer_byte_limit: int,
                 window_limit: int = 16):
        self.division = division
        self.follower = follower
        self.heartbeat_interval_s = heartbeat_interval_s
        self.buffer_byte_limit = buffer_byte_limit
        self.window_limit = max(1, window_limit)
        self.sender = division.server.replication.acquire(
            follower.peer_id, self)
        self._running = False
        self._epoch = 0        # bumped on window reset; stale replies ignored
        self._inflight = 0     # pipelined (non-heartbeat) requests outstanding
        # In-flight FRAMES carrying this group's items.  The bound is the
        # sender's per-group window (raft.tpu.replication.window-depth):
        # 1 = the classic one-envelope-at-a-time FIFO latch; >1 (sequenced
        # lanes only) lets collect() cut the next batch from the
        # speculative next-index while earlier frames are still on the
        # wire, hiding the append round trip (GrpcLogAppender.java:343's
        # sliding window, batched across groups).
        self._frames = 0
        self._frame_limit = max(1, getattr(self.sender, "group_window", 1))
        self._probe_due = False
        self._last_send_s = 0.0
        self._backoff_until = 0.0
        self._last_error_log_s = 0.0
        self._prefaulting = False
        self._ci_countdown = 0  # commit-infos piggyback thinning
        # follower accepted a hibernate request (division.hibernate_sweep);
        # cleared on wake / any send / window reset
        self.hibernate_acked = False
        self._pending_sends: set[asyncio.Task] = set()

    def start(self) -> None:
        self._running = True
        # Initial empty append: announces leadership and probes the follower
        # log position right away (the reference appender sends immediately
        # on start; followers learn leader identity from this probe).
        self._probe_due = True
        self.sender.mark(self)

    async def stop(self) -> None:
        self._running = False
        self.sender.unmark(self)
        # stop() can be reached from INSIDE one of this appender's own
        # pending tasks (e.g. _send_heartbeat's reply carries a higher term
        # -> change_to_follower -> ctx.stop -> this): never cancel-and-await
        # the task we are currently running in — the pending
        # self-cancellation would detonate at the next await and abort the
        # rest of the step-down cleanup.
        cur = asyncio.current_task()
        tasks = [t for t in self._pending_sends if t is not cur]
        self._pending_sends.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        # Retire the shared per-destination sender when this was its last
        # appender (otherwise departed peers leak standing flush tasks).
        await self.division.server.replication.release(
            self.follower.peer_id, self)

    def notify(self) -> None:
        if self._running:
            self.sender.mark(self)

    def _build_request(self, next_idx: int, heartbeat: bool = False
                       ) -> Optional[AppendEntriesRequest]:
        div = self.division
        log = div.state.log
        if next_idx < log.start_index:
            return None  # needs snapshot (handled by caller)
        prev: Optional[TermIndex] = None
        if next_idx > 0:
            prev = log.term_at_or_before(next_idx - 1)
            if prev is None and next_idx - 1 >= log.start_index:
                return None
            if prev is None and not div.snapshot_covers(next_idx - 1):
                prev = None  # empty log start
            elif prev is None:
                prev = div.snapshot_term_index(next_idx - 1)
                if prev is None:
                    return None
        if heartbeat:
            entries = ()
        else:
            entries = tuple(log.get_entries(next_idx, log.next_index,
                                            self.buffer_byte_limit))
        # Cluster-wide commit picture piggyback (CommitInfoCache): on every
        # probe/heartbeat, but only every 8th data batch — the infos are
        # advisory (commit levels for *_COMMITTED watches and group-info),
        # and rebuilding + re-parsing them per batch taxed the hot path.
        self._ci_countdown -= 1
        if heartbeat or self._ci_countdown <= 0:
            self._ci_countdown = 8
            infos = div.get_commit_infos_wire()
        else:
            infos = ()
        return AppendEntriesRequest(
            header=RaftRpcHeader(div.member_id.peer_id, self.follower.peer_id,
                                 div.group_id),
            leader_term=div.state.current_term,
            previous=prev,
            entries=entries,
            leader_commit=log.get_last_committed_index(),
            commit_infos=infos,
        )

    # -------------------------------------------------------------- window

    def _reset_window(self, *, rewind_to: Optional[int] = None,
                      backoff_s: float = 0.0) -> None:
        """Discard the pipeline: ignore everything in flight, rewind the send
        cursor (reference resetClient: follower.decreaseNextIndex + clear the
        request map)."""
        self._epoch += 1
        self._inflight = 0
        self.hibernate_acked = False  # the follower's timer may be re-armed
        f = self.follower
        # NB: the rewind target is deliberately NOT floored at log.start_index
        # — next_index < start_index is exactly what routes collect() into
        # the snapshot-install path for a follower behind the purged log.
        if rewind_to is not None:
            target = max(rewind_to, 0)
            if target <= f.match_index:
                # The follower's INCONSISTENCY hint is authoritative: it has
                # lost entries past its recorded match (possible only with a
                # volatile log, e.g. memory-log restart) — regress the match
                # so commit quorum math stays honest.
                f.match_index = target - 1
                self.division.on_follower_match_regressed(f)
            f.next_index = target
        else:
            f.next_index = max(f.match_index + 1, 0)
        if backoff_s > 0:
            self._backoff_until = time.monotonic() + backoff_s
        if self._running:
            self.sender.mark(self)

    @staticmethod
    def _approx_bytes(request) -> int:
        """Cheap request-size estimate for the envelope byte budget (the
        exact serialized size was already paid once inside get_entries; do
        not serialize again here)."""
        total = 128
        for e in request.entries:
            if e.smlog is not None:
                total += (len(e.smlog.log_data)
                          + len(e.smlog.sm_data or b"") + 48)
            else:
                total += 64
        return total

    def collect(self, out: list, budget: int) -> int:
        """Drain this follower's due sends into ``out`` (PeerSender flush):
        the start probe, then window fills until the window is full, the
        byte budget is spent, or the log is drained.  Returns the
        (approximate) bytes added.  The busy latch guarantees a group's
        items are never split across two racing envelopes."""
        div = self.division
        f = self.follower
        if not self._running or not div.is_leader() \
                or self._frames >= self._frame_limit:
            return 0
        now = time.monotonic()
        if now < self._backoff_until:
            return 0
        added = 0
        # Count the frame BEFORE anything can be appended to out: if a
        # later fill iteration raises, already-collected items still ship
        # in this flush's envelope — without the latch a re-mark could
        # split this group's items across two racing envelopes.  At frame
        # limit 1 that is the full FIFO guarantee; above it, racing frames
        # are ordered by the sequenced-lane intake instead.  Un-count on
        # the no-item path at the end.
        self._frames += 1
        try:
            if self._probe_due:
                probe = self._build_request(f.next_index, heartbeat=True)
                if probe is not None:
                    self._probe_due = False
                    self._last_send_s = now
                    added += 128
                    out.append(OutItem(self, probe, self._epoch, False))
            log = div.state.log
            while (self._inflight < self.window_limit
                   and not f.snapshot_in_progress and added <= budget):
                next_idx = f.next_index
                if next_idx >= log.next_index:
                    break  # fully caught up (at send level)
                if not log.is_resident(next_idx):
                    # evicted segment: fault it in off-loop, then resume — a
                    # synchronous multi-MB read+decode here would stall every
                    # division's heartbeats and election timers
                    if not self._prefaulting:
                        self._prefaulting = True
                        self._spawn(self._prefault(next_idx))
                    break
                request = self._build_request(next_idx)
                if request is None:
                    # behind the purged log -> snapshot path, serialized by
                    # the snapshot_in_progress flag in try_install_snapshot
                    self._spawn(self._install_snapshot())
                    break
                if not request.entries:
                    break
                f.next_index = request.entries[-1].index + 1
                self._inflight += 1
                self._last_send_s = now
                added += self._approx_bytes(request)
                out.append(OutItem(self, request, self._epoch, True))
        finally:
            if not added:
                self._frames -= 1
            else:
                # any send re-arms the follower's election timer: a stale
                # hibernate ack must not let the leader fall asleep without
                # a fresh handshake
                self.hibernate_acked = False
        return added

    def has_backlog(self) -> bool:
        """Entries remain past the send cursor AND the frame window has
        room: the sweep's drain pass uses this to keep cutting frames for
        this group in the SAME pass (pipelining), instead of waiting out
        the in-flight frame's round trip for the envelope_done re-mark."""
        return (self._running and self._frames < self._frame_limit
                and not self.follower.snapshot_in_progress
                and self.division.is_leader()
                and self.division.state.log.next_index
                > self.follower.next_index)

    def envelope_done(self, remark: bool = True) -> None:
        """An envelope carrying this appender's items completed (all its
        replies/errors dispatched): release its frame-window slot and
        re-mark so the next flush refills the window."""
        self._frames = max(0, self._frames - 1)
        if remark and self._running and self.division.is_leader():
            self.sender.mark(self)

    def on_send_error(self, item, e: Exception) -> None:
        """An envelope / unary send carrying ``item`` failed."""
        if item.epoch != self._epoch or not self._running:
            return
        # Connection trouble: drop the pipeline, retry after a pause paced
        # by the heartbeat timer (GrpcLogAppender.onError).  Log
        # (rate-limited) — a silent persistent error here looks like a
        # wedged follower with no trace of why.
        now = time.monotonic()
        if now - self._last_error_log_s > 2.0:
            self._last_error_log_s = now
            LOG.warning("%s -> %s append failed (epoch %d): %s",
                        self.division.member_id, self.follower.peer_id,
                        self._epoch, e)
        self._reset_window(backoff_s=self.heartbeat_interval_s)

    async def on_send_reply(self, item, reply: AppendEntriesReply,
                            ack_sink: Optional[list] = None) -> None:
        """``ack_sink`` (sweep mode): collect this reply's engine ack as a
        packed row instead of a scalar on_ack call — the PeerSender feeds
        the whole envelope's rows to QuorumEngine.on_ack_batch at once."""
        if item.epoch != self._epoch or not self._running:
            return  # window was reset while this was in flight
        if item.pipelined:
            self._inflight -= 1
        await self._on_reply(item.request, reply, item.epoch, ack_sink)

    def _spawn(self, coro) -> None:
        t = asyncio.create_task(coro)
        self._pending_sends.add(t)
        t.add_done_callback(self._pending_sends.discard)

    async def _install_snapshot(self) -> None:
        div = self.division
        handled = await div.try_install_snapshot(self.follower)
        if handled:
            self.notify()

    async def _prefault(self, index: int) -> None:
        try:
            await asyncio.to_thread(self.division.state.log.prefault, index)
        finally:
            self._prefaulting = False
        self.notify()

    async def _send_heartbeat(self, request: AppendEntriesRequest,
                              epoch: int) -> None:
        """The unary dedicated heartbeat channel (reference cost shape,
        used when bulk-heartbeat coalescing is disabled): outside the
        PeerSender window, never queued behind a full data pipeline."""
        div = self.division
        try:
            reply = await div.server.send_server_rpc(
                self.follower.peer_id, request)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.on_send_error(OutItem(self, request, epoch, False), e)
            return
        if epoch != self._epoch or not self._running:
            return  # window was reset while this was in flight
        await self._on_reply(request, reply, epoch)
        self.notify()

    def heartbeat_item(self, now: float,
                       hibernate: bool = False) -> Optional[tuple]:
        """Contribute this follower's compact item to the sweep's
        BulkHeartbeat toward its destination server, or None when not due
        (recent traffic doubles as a heartbeat, exactly like the unary
        path).  Also doubles as the periodic fill-retry waker.  With
        ``hibernate`` the item carries the hibernate flag, asking the follower
        to disarm its election timer (idle-group quiescence)."""
        div = self.division
        if not self._running or not div.is_leader():
            return None
        f = self.follower
        # Fill-retry mark only when a fill could actually produce work:
        # pending data, a due probe, or an expired backoff.  Marking every
        # appender every sweep made the PeerSender flush loop re-collect
        # thousands of idle appenders per interval (profiling at 1024
        # groups: 6 collect calls per actual send).
        if self._backoff_until and now >= self._backoff_until:
            # one-shot: clear on expiry, or every later sweep re-marks an
            # idle appender forever once it has had a single send error
            self._backoff_until = 0.0
            self.sender.mark(self)
        elif self._probe_due or div.state.log.next_index > f.next_index:
            self.sender.mark(self)
        if not div.hibernating:
            # while asleep the ONLY traffic is the backstop slow tick, so
            # ack clocks are legitimately backstop/4 old — judging that as
            # follower slowness would spam notifications for silence the
            # leader itself requested
            div.check_follower_slowness(f)
        # Due-ness keys on CONFIRMED contact (the follower's replies), not
        # on queueing: a data batch stamps _last_send_s when it enters an
        # envelope, and under congestion that envelope can sit queued (or
        # time out) while the follower hears silence past its election
        # timeout — measured at 5-peer x 10240 bring-up, thousands of
        # healthy leaders were deposed by followers whose p50 silence was
        # 17.8s.  Policy for a follower that stops replying: up to TWO
        # heartbeat attempts per interval (the 0.45*hb send cap), so an
        # unresponsive peer costs at most 2x the idle item volume.
        # _last_send_s == 0.0 is the explicit force-due marker (hibernation
        # wake sets it: "next sweep heartbeats immediately").
        hb = self.heartbeat_interval_s
        if self._last_send_s:
            if now - f.last_rpc_response_s < hb * 0.9:
                return None  # follower demonstrably fresh (recent reply)
            if now - self._last_send_s < hb * 0.45:
                return None  # give the in-flight contact a chance to land
        if f.snapshot_in_progress:
            return None
        # NB: _backoff_until deliberately does NOT suppress the compact
        # heartbeat — the data window pauses on send errors, but this is
        # exactly the contact that must keep flowing while it does (the
        # reference's separate heartbeat channel has the same property,
        # GrpcLogAppender heartbeat channel).
        log = div.state.log
        commit = log.get_last_committed_index()
        self._last_send_s = now
        cti = log.get_term_index(commit) if commit >= 0 else None
        base = (div.group_id.to_bytes(), div.state.current_term, commit,
                cti.term if cti is not None else -1)
        # hibernate request rides as a 5th flag field so the item still
        # carries real commit info (a lagging follower must be able to
        # catch its commit up from these very items to pass the sync gate)
        return base + (1,) if hibernate else base

    def next_due(self, now: float) -> float:
        """Earliest time ``heartbeat_item`` could next produce an item,
        derived from the same confirmed-contact gate (upkeep plane's
        CH_HEARTBEAT arm).  Conservative-EARLY by construction: the gate
        re-checks at dispatch, so an early deadline costs one declined
        call, never a changed decision — and a LATE one is impossible
        because every input that moves the true due-time earlier
        (wake/leadership/conf-change) sets the force-due marker or re-arms
        the slot.  ``_last_send_s == 0.0`` is that marker: due now."""
        if not self._last_send_s:
            return now
        hb = self.heartbeat_interval_s
        return max(self.follower.last_rpc_response_s + hb * 0.9,
                   self._last_send_s + hb * 0.45)

    async def on_bulk_reply(self, code: int, term: int, next_index: int,
                            follower_commit: int, flush_index: int,
                            ack_sink: Optional[list] = None) -> None:
        """Dispatch one aligned BulkHeartbeatReply item.  Happy path keeps
        the follower fresh (staleness + watch frontiers); any anomaly
        escalates to a full AppendEntries probe on the data path, which
        carries the prev check the compact item omits."""
        from ratis_tpu.protocol.raftrpc import (BULK_HB_HIBERNATED,
                                                BULK_HB_OK,
                                                BULK_HB_UNKNOWN_GROUP)
        div = self.division
        if not self._running or not div.is_leader():
            return
        if code == BULK_HB_UNKNOWN_GROUP:
            return  # peer doesn't host this group (e.g. mid group-add)
        if term > div.state.current_term:
            await div.change_to_follower(
                term, None, reason="higher term in bulk heartbeat reply")
            return
        if code == BULK_HB_HIBERNATED:
            # follower disarmed its election timer: this channel may sleep
            self.hibernate_acked = True
            f = self.follower
            f.last_rpc_response_s = time.monotonic()
            div.on_follower_heartbeat_ack(f, ack_sink)
            return
        self.hibernate_acked = False  # any other reply: timer is armed
        if code != BULK_HB_OK:
            # stale NOT_LEADER at <= our term, or BUSY (the item was skipped
            # because our own in-flight append holds the division's lock —
            # that append doubles as the heartbeat): ignore, retry next sweep
            return
        f = self.follower
        f.last_rpc_response_s = time.monotonic()
        if follower_commit > f.commit_index:
            f.commit_index = follower_commit
            div.update_commit_info(f.peer_id, follower_commit)
        div.on_follower_heartbeat_ack(f, ack_sink)
        log = div.state.log
        if (next_index < f.next_index and self._inflight == 0
                and self._frames == 0):
            # Follower's log ends before our send cursor with nothing in
            # flight: it lost entries (restart) or our cursor is stale.
            # Send a full probe so the INCONSISTENCY path decides with
            # prev-check fidelity (including the match-regress protocol).
            self._probe_due = True
            self.sender.mark(self)
        elif log.next_index > f.next_index:
            self.sender.mark(self)  # data pending: wake the fill path

    async def _on_reply(self, request: AppendEntriesRequest,
                        reply: AppendEntriesReply, epoch: int,
                        ack_sink: Optional[list] = None) -> None:
        div = self.division
        if reply.term > div.state.current_term:
            await div.change_to_follower(reply.term, leader_id=None,
                                         reason="higher term in append reply")
            return
        if reply.result == AppendResult.SUCCESS:
            self.follower.commit_index = max(self.follower.commit_index,
                                             reply.follower_commit)
            div.update_commit_info(self.follower.peer_id,
                                   reply.follower_commit)
            # Cap the confirmed match at what THIS request actually verified
            # against our log (prev check + entries sent).  The follower's
            # raw flush_index may cover a stale tail from a previous term
            # that a heartbeat never examined; counting it toward quorum
            # could commit entries that are not truly replicated.
            last_covered = (request.entries[-1].index if request.entries
                            else (request.previous.index if request.previous
                                  else -1))
            confirmed = min(reply.match_index, last_covered)
            if self.follower.update_match(confirmed):
                div.on_follower_ack(self.follower, ack_sink)
            else:
                div.on_follower_heartbeat_ack(self.follower, ack_sink)
        elif reply.result == AppendResult.INCONSISTENCY:
            if epoch == self._epoch:
                # observable reorder/rewind churn (ADVICE r5): the keyed
                # gRPC stream dispatch should keep this at ~0 under load
                m = div.server.replication.metrics
                m["rewinds"] = m.get("rewinds", 0) + 1
                if self._frames > 1 or self._inflight > 0:
                    # windowed rewind: >0 unacked pipelined frames beyond
                    # this one are being dropped (epoch bump) and the lane
                    # re-cuts from the rewound next-index — not a full
                    # per-destination reset
                    m["windowed_rewinds"] = \
                        m.get("windowed_rewinds", 0) + 1
                hint = min(reply.next_index,
                           max(request.previous.index if request.previous
                               else 0, 0))
                f = self.follower
                if hint <= f.match_index and (
                        request.previous is None
                        or request.previous.index != f.match_index):
                    # Heartbeats travel unary/coalesced while entry appends
                    # ride the ordered stream, so a stale heartbeat's
                    # INCONSISTENCY can land after a newer SUCCESS raised
                    # match in the same epoch.  This request never examined
                    # our recorded match position, so its rejection is not
                    # authoritative for a regress: reset the window and
                    # re-probe at the match instead.  A genuine volatile-log
                    # restart fails the probe (previous.index == match) too
                    # and regresses then, via the authoritative branch.
                    self._reset_window()
                else:
                    self._reset_window(rewind_to=hint)
        elif reply.result == AppendResult.NOT_LEADER:
            # stale term on our side already handled above; otherwise ignore
            pass

    # ----------------------------------------------------------- heartbeats

    def on_heartbeat_sweep(self, now: float) -> None:
        """One iteration of the unary dedicated heartbeat channel, driven by
        the SERVER-level sweep (server.HeartbeatScheduler) when bulk
        coalescing is disabled.  Semantics match the reference's dedicated
        heartbeat stream: an empty AppendEntries goes out whenever nothing
        else has been sent for an interval, regardless of window occupancy
        (GrpcLogAppender.java:172)."""
        div = self.division
        if not self._running or not div.is_leader():
            return
        self.sender.mark(self)  # periodic fill retry (backoff expiry etc.)
        try:
            div.check_follower_slowness(self.follower)
            # same confirmed-contact due-ness as heartbeat_item: a QUEUED
            # (or erroring, backed-off) data batch must not suppress the
            # dedicated heartbeat while the follower hears silence — the
            # deposal mechanism was identical on this path
            f = self.follower
            interval = self.heartbeat_interval_s
            if self._last_send_s:
                if now - f.last_rpc_response_s < interval * 0.9:
                    return  # follower demonstrably fresh (recent reply)
                if now - self._last_send_s < interval * 0.45:
                    return
            hb = self._build_request(self.follower.next_index,
                                     heartbeat=True)
            if hb is None:
                return  # snapshot path owns this follower right now
            self._last_send_s = now
            self._spawn(self._send_heartbeat(hb, self._epoch))
        except Exception:
            # the sweep must never die on one follower's error — the mark
            # above already ran, so fills keep retrying regardless
            LOG.exception("%s heartbeat sweep iteration failed",
                          self.division.member_id)


class LeaderContext:
    """Everything that exists only while this division leads
    (reference LeaderStateImpl minus the event thread)."""

    def __init__(self, division, properties=None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        self.division = division
        p = division.server.properties
        self.pending = PendingRequests(
            RaftServerConfigKeys.Write.element_limit(p),
            RaftServerConfigKeys.Write.byte_limit(p),
            mirror=division._engine_set_pending)
        self.followers: dict[RaftPeerId, FollowerInfo] = {}
        self.appenders: dict[RaftPeerId, LogAppender] = {}
        self.startup_index: int = -1  # the conf entry appended on election
        self.leader_ready = asyncio.get_running_loop().create_future()
        # shared with the server-level HeartbeatScheduler sweep — the two
        # cadences must agree or heartbeat gaps silently grow
        self._heartbeat_interval_s = division.server.heartbeat_interval_s
        self._buffer_byte_limit = \
            RaftServerConfigKeys.Log.Appender.buffer_byte_limit(p)
        self._window_limit = \
            RaftServerConfigKeys.Log.Appender.pipeline_window(p)
        from ratis_tpu.metrics import LogAppenderMetrics
        self.appender_metrics = LogAppenderMetrics(division.member_id)

    def start_appenders(self) -> None:
        div = self.division
        next_index = div.state.log.next_index
        for peer in div.state.configuration.all_peers():
            if peer.id == div.member_id.peer_id:
                continue
            self.add_follower(peer.id, next_index)

    def add_follower(self, peer_id: RaftPeerId, next_index: int) -> None:
        if peer_id in self.followers:
            return
        info = FollowerInfo(peer_id, next_index)
        self.followers[peer_id] = info
        appender = LogAppender(self.division, info, self._heartbeat_interval_s,
                               self._buffer_byte_limit, self._window_limit)
        self.appenders[peer_id] = appender
        self.appender_metrics.add_follower_gauges(
            peer_id, lambda i=info: i.next_index,
            lambda i=info: i.match_index,
            lambda i=info: time.monotonic() - i.last_rpc_response_s)
        appender.start()
        # a freshly-added appender is due immediately; in array mode the
        # division's CH_HEARTBEAT slot must hear about it or the plane
        # would wait out the previously-armed deadline
        self.division.upkeep_touch_heartbeat()

    async def remove_follower(self, peer_id: RaftPeerId) -> None:
        self.followers.pop(peer_id, None)
        self.appender_metrics.remove_follower_gauges(peer_id)
        a = self.appenders.pop(peer_id, None)
        if a is not None:
            await a.stop()

    def notify_appenders(self) -> None:
        for a in self.appenders.values():
            a.notify()

    async def stop(self, exception: Optional[NotLeaderException] = None) -> None:
        for a in list(self.appenders.values()):
            await a.stop()
        self.appenders.clear()
        self.appender_metrics.unregister()
        if exception is not None:
            # StateMachine.notifyNotLeader (StateMachine.java:241): the SM
            # sees the client requests that will never commit here, before
            # their futures fail with NotLeaderException.
            pending_reqs = self.pending.requests()
            if pending_reqs:
                try:
                    await self.division.state_machine.notify_not_leader(
                        pending_reqs)
                except Exception:
                    LOG.exception("%s notify_not_leader raised",
                                  self.division.member_id)
            self.pending.drain_not_leader(exception)
        if not self.leader_ready.done():
            self.leader_ready.cancel()
