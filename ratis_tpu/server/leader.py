"""Leader-side machinery: pending requests, watch bookkeeping, log appenders.

Capability parity with the reference LeaderStateImpl + LogAppender
(ratis-server/.../impl/LeaderStateImpl.java:101, PendingRequests.java:51,
leader/LogAppenderBase.java:50, LogAppenderDefault.java:43): per-follower
replication drivers with batched AppendEntries and nextIndex backoff, a
pending-request registry completed on apply, and step-down draining.

Differences from the reference by design: there is no per-group
EventProcessor thread — commit advancement happens in the server-wide
QuorumEngine (ratis_tpu.engine) and calls back into the division.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ratis_tpu.protocol.exceptions import (NotLeaderException,
                                           ResourceUnavailableException)
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.raftrpc import (AppendEntriesReply,
                                        AppendEntriesRequest, AppendResult,
                                        RaftRpcHeader)
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest
from ratis_tpu.protocol.termindex import TermIndex

LOG = logging.getLogger(__name__)


class PendingRequest:
    def __init__(self, index: int, request: RaftClientRequest):
        self.index = index
        self.request = request
        self.future: asyncio.Future = asyncio.get_event_loop().create_future()

    def set_reply(self, reply: RaftClientReply) -> None:
        if not self.future.done():
            self.future.set_result(reply)

    def fail(self, exception: Exception) -> None:
        if not self.future.done():
            self.future.set_result(
                RaftClientReply.failure_reply(self.request, exception))


class PendingRequests:
    """index -> in-flight client write, with byte/element permits
    (reference PendingRequests.java:51,100-110)."""

    def __init__(self, element_limit: int = 4096, byte_limit: int = 64 << 20):
        self._map: dict[int, PendingRequest] = {}
        self._element_limit = element_limit
        self._byte_limit = byte_limit
        self._bytes = 0

    def add(self, index: int, request: RaftClientRequest) -> PendingRequest:
        size = request.message.size()
        if (len(self._map) >= self._element_limit
                or (self._bytes + size) > self._byte_limit):
            raise ResourceUnavailableException(
                f"pending requests full: {len(self._map)} elements, "
                f"{self._bytes} bytes")
        p = PendingRequest(index, request)
        self._map[index] = p
        self._bytes += size
        return p

    def pop(self, index: int) -> Optional[PendingRequest]:
        p = self._map.pop(index, None)
        if p is not None:
            self._bytes -= p.request.message.size()
        return p

    def drain_not_leader(self, exception: NotLeaderException) -> int:
        """Step-down: fail everything (PendingRequests.notifyNotLeader)."""
        n = len(self._map)
        for p in self._map.values():
            p.fail(exception)
        self._map.clear()
        self._bytes = 0
        return n

    def __len__(self) -> int:
        return len(self._map)


class FollowerInfo:
    """Leader's view of one follower (reference server-api leader/FollowerInfo)."""

    def __init__(self, peer_id: RaftPeerId, next_index: int):
        self.peer_id = peer_id
        self.next_index = next_index
        self.match_index = -1
        self.commit_index = -1  # piggybacked on append replies
        self.snapshot_in_progress = False
        self.attend_vote = True  # False for listeners
        self.last_rpc_response_s = time.monotonic()

    def update_match(self, match: int) -> bool:
        self.last_rpc_response_s = time.monotonic()
        if match > self.match_index:
            self.match_index = match
            return True
        return False

    def decrease_next_index(self, hint: int) -> None:
        """INCONSISTENCY backoff (LogAppenderDefault.java:187)."""
        self.next_index = max(0, min(hint, self.next_index - 1))


class LogAppender:
    """One leader->follower replication driver as an asyncio task
    (reference GrpcLogAppender pipelining is approximated by issuing the next
    batch immediately after each ack; heartbeats fire on idle timeout)."""

    def __init__(self, division, follower: FollowerInfo,
                 heartbeat_interval_s: float, buffer_byte_limit: int):
        self.division = division
        self.follower = follower
        self.heartbeat_interval_s = heartbeat_interval_s
        self.buffer_byte_limit = buffer_byte_limit
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(
            self._run(), name=f"appender-{self.division.member_id}-{self.follower.peer_id}")

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._wake.set()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def notify(self) -> None:
        self._wake.set()

    def _build_request(self) -> Optional[AppendEntriesRequest]:
        div = self.division
        log = div.state.log
        next_idx = self.follower.next_index
        if next_idx < log.start_index:
            return None  # needs snapshot (handled by caller)
        prev: Optional[TermIndex] = None
        if next_idx > 0:
            prev = log.term_at_or_before(next_idx - 1)
            if prev is None and next_idx - 1 >= log.start_index:
                return None
            if prev is None and not div.snapshot_covers(next_idx - 1):
                prev = None  # empty log start
            elif prev is None:
                prev = div.snapshot_term_index(next_idx - 1)
                if prev is None:
                    return None
        entries = log.get_entries(next_idx, log.next_index,
                                  self.buffer_byte_limit)
        return AppendEntriesRequest(
            header=RaftRpcHeader(div.member_id.peer_id, self.follower.peer_id,
                                 div.group_id),
            leader_term=div.state.current_term,
            previous=prev,
            entries=tuple(entries),
            leader_commit=log.get_last_committed_index(),
        )

    async def _run(self) -> None:
        div = self.division
        while self._running and div.is_leader():
            request = self._build_request()
            if request is None:
                # follower is behind the purged log -> snapshot path
                handled = await div.try_install_snapshot(self.follower)
                if not handled:
                    await asyncio.sleep(self.heartbeat_interval_s)
                continue
            try:
                reply = await div.server.send_server_rpc(
                    self.follower.peer_id, request)
            except Exception:
                await asyncio.sleep(self.heartbeat_interval_s)
                continue
            if not self._running or not div.is_leader():
                break
            await self._on_reply(request, reply)
            # Idle wait: wake on new entries or heartbeat deadline
            if self.follower.next_index >= div.state.log.next_index:
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           self.heartbeat_interval_s)
                except asyncio.TimeoutError:
                    pass

    async def _on_reply(self, request: AppendEntriesRequest,
                        reply: AppendEntriesReply) -> None:
        div = self.division
        if reply.term > div.state.current_term:
            await div.change_to_follower(reply.term, leader_id=None,
                                         reason="higher term in append reply")
            return
        if reply.result == AppendResult.SUCCESS:
            last_sent = (request.entries[-1].index if request.entries
                         else (request.previous.index if request.previous else -1))
            self.follower.next_index = max(self.follower.next_index, last_sent + 1)
            self.follower.commit_index = max(self.follower.commit_index,
                                             reply.follower_commit)
            if self.follower.update_match(reply.match_index):
                div.on_follower_ack(self.follower)
            else:
                div.on_follower_heartbeat_ack(self.follower)
        elif reply.result == AppendResult.INCONSISTENCY:
            self.follower.decrease_next_index(reply.next_index)
        elif reply.result == AppendResult.NOT_LEADER:
            # stale term on our side already handled above; otherwise ignore
            pass


class LeaderContext:
    """Everything that exists only while this division leads
    (reference LeaderStateImpl minus the event thread)."""

    def __init__(self, division, properties=None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        self.division = division
        p = division.server.properties
        self.pending = PendingRequests(
            RaftServerConfigKeys.Write.element_limit(p),
            RaftServerConfigKeys.Write.byte_limit(p))
        self.followers: dict[RaftPeerId, FollowerInfo] = {}
        self.appenders: dict[RaftPeerId, LogAppender] = {}
        self.startup_index: int = -1  # the conf entry appended on election
        self.leader_ready = asyncio.get_event_loop().create_future()
        hb = RaftServerConfigKeys.Rpc.timeout_min(p).seconds / 2
        self._heartbeat_interval_s = hb
        self._buffer_byte_limit = \
            RaftServerConfigKeys.Log.Appender.buffer_byte_limit(p)
        from ratis_tpu.metrics import LogAppenderMetrics
        self.appender_metrics = LogAppenderMetrics(division.member_id)

    def start_appenders(self) -> None:
        div = self.division
        next_index = div.state.log.next_index
        for peer in div.state.configuration.all_peers():
            if peer.id == div.member_id.peer_id:
                continue
            self.add_follower(peer.id, next_index)

    def add_follower(self, peer_id: RaftPeerId, next_index: int) -> None:
        if peer_id in self.followers:
            return
        info = FollowerInfo(peer_id, next_index)
        self.followers[peer_id] = info
        appender = LogAppender(self.division, info, self._heartbeat_interval_s,
                               self._buffer_byte_limit)
        self.appenders[peer_id] = appender
        self.appender_metrics.add_follower_gauges(
            peer_id, lambda i=info: i.next_index,
            lambda i=info: i.match_index,
            lambda i=info: time.monotonic() - i.last_rpc_response_s)
        appender.start()

    async def remove_follower(self, peer_id: RaftPeerId) -> None:
        self.followers.pop(peer_id, None)
        self.appender_metrics.remove_follower_gauges(peer_id)
        a = self.appenders.pop(peer_id, None)
        if a is not None:
            await a.stop()

    def notify_appenders(self) -> None:
        for a in self.appenders.values():
            a.notify()

    async def stop(self, exception: Optional[NotLeaderException] = None) -> None:
        for a in list(self.appenders.values()):
            await a.stop()
        self.appenders.clear()
        self.appender_metrics.unregister()
        if exception is not None:
            self.pending.drain_not_leader(exception)
        if not self.leader_ready.done():
            self.leader_ready.cancel()
