"""Leader-side machinery: pending requests, watch bookkeeping, log appenders.

Capability parity with the reference LeaderStateImpl + LogAppender
(ratis-server/.../impl/LeaderStateImpl.java:101, PendingRequests.java:51,
leader/LogAppenderBase.java:50, LogAppenderDefault.java:43): per-follower
replication drivers with batched AppendEntries and nextIndex backoff, a
pending-request registry completed on apply, and step-down draining.

Differences from the reference by design: there is no per-group
EventProcessor thread — commit advancement happens in the server-wide
QuorumEngine (ratis_tpu.engine) and calls back into the division.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ratis_tpu.protocol.exceptions import (NotLeaderException,
                                           ResourceUnavailableException)
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.raftrpc import (AppendEntriesReply,
                                        AppendEntriesRequest, AppendResult,
                                        RaftRpcHeader)
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest
from ratis_tpu.protocol.termindex import TermIndex

LOG = logging.getLogger(__name__)


class PendingRequest:
    def __init__(self, index: int, request: RaftClientRequest):
        self.index = index
        self.request = request
        self.future: asyncio.Future = asyncio.get_event_loop().create_future()

    def set_reply(self, reply: RaftClientReply) -> None:
        if not self.future.done():
            self.future.set_result(reply)

    def fail(self, exception: Exception) -> None:
        if not self.future.done():
            self.future.set_result(
                RaftClientReply.failure_reply(self.request, exception))


class PendingRequests:
    """index -> in-flight client write, with byte/element permits
    (reference PendingRequests.java:51,100-110)."""

    def __init__(self, element_limit: int = 4096, byte_limit: int = 64 << 20):
        self._map: dict[int, PendingRequest] = {}
        self._element_limit = element_limit
        self._byte_limit = byte_limit
        self._bytes = 0

    def add(self, index: int, request: RaftClientRequest) -> PendingRequest:
        size = request.message.size()
        if (len(self._map) >= self._element_limit
                or (self._bytes + size) > self._byte_limit):
            raise ResourceUnavailableException(
                f"pending requests full: {len(self._map)} elements, "
                f"{self._bytes} bytes")
        p = PendingRequest(index, request)
        self._map[index] = p
        self._bytes += size
        return p

    def pop(self, index: int) -> Optional[PendingRequest]:
        p = self._map.pop(index, None)
        if p is not None:
            self._bytes -= p.request.message.size()
        return p

    def requests(self) -> list[RaftClientRequest]:
        return [p.request for p in self._map.values()]

    def drain_not_leader(self, exception: NotLeaderException) -> int:
        """Step-down: fail everything (PendingRequests.notifyNotLeader)."""
        n = len(self._map)
        for p in self._map.values():
            p.fail(exception)
        self._map.clear()
        self._bytes = 0
        return n

    def __len__(self) -> int:
        return len(self._map)


class FollowerInfo:
    """Leader's view of one follower (reference server-api leader/FollowerInfo)."""

    def __init__(self, peer_id: RaftPeerId, next_index: int):
        self.peer_id = peer_id
        self.next_index = next_index
        self.match_index = -1
        self.commit_index = -1  # piggybacked on append replies
        self.snapshot_in_progress = False
        self.attend_vote = True  # False for listeners
        self.last_rpc_response_s = time.monotonic()

    def update_match(self, match: int) -> bool:
        self.last_rpc_response_s = time.monotonic()
        if match > self.match_index:
            self.match_index = match
            return True
        return False

class LogAppender:
    """One leader->follower replication driver with a pipelined send window.

    Mirrors the reference GrpcLogAppender (GrpcLogAppender.java:343-381):
    up to ``window_limit`` AppendEntries requests are in flight at once —
    ``follower.next_index`` is the optimistic *send* cursor, advanced when a
    batch is handed to the transport, while ``follower.match_index`` advances
    only on acks.  Replies may complete out of order.  Per-link FIFO
    delivery (TCP/simulated transports) keeps the pipeline efficient; it is
    NOT a correctness requirement: reordered delivery (possible with
    concurrent unary gRPC handlers) at worst produces a spurious
    INCONSISTENCY -> window reset + resend, and match only ever advances
    from per-request-capped SUCCESS confirmations.  A dedicated heartbeat timer
    (reference's separate heartbeat channel, GrpcLogAppender.java:172) fires
    outside the window and is never queued behind a full pipeline.  On
    INCONSISTENCY or an RPC error the window resets: the epoch is bumped so
    in-flight completions from before the reset are ignored, and the send
    cursor rewinds (GrpcLogAppender.onError/resetClient:475-530).
    """

    def __init__(self, division, follower: FollowerInfo,
                 heartbeat_interval_s: float, buffer_byte_limit: int,
                 window_limit: int = 16):
        self.division = division
        self.follower = follower
        self.heartbeat_interval_s = heartbeat_interval_s
        self.buffer_byte_limit = buffer_byte_limit
        self.window_limit = max(1, window_limit)
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._epoch = 0        # bumped on window reset; stale replies ignored
        self._inflight = 0     # pipelined (non-heartbeat) requests outstanding
        self._last_send_s = 0.0
        self._backoff_until = 0.0
        self._last_error_log_s = 0.0
        self._prefaulting = False
        self._pending_sends: set[asyncio.Task] = set()

    def start(self) -> None:
        self._running = True
        name = f"appender-{self.division.member_id}-{self.follower.peer_id}"
        self._task = asyncio.create_task(self._run(), name=name)

    async def stop(self) -> None:
        self._running = False
        self._wake.set()
        tasks = list(self._pending_sends)
        if self._task is not None:
            tasks.append(self._task)
        self._task = None
        self._pending_sends.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    def notify(self) -> None:
        self._wake.set()

    def _build_request(self, next_idx: int, heartbeat: bool = False
                       ) -> Optional[AppendEntriesRequest]:
        div = self.division
        log = div.state.log
        if next_idx < log.start_index:
            return None  # needs snapshot (handled by caller)
        prev: Optional[TermIndex] = None
        if next_idx > 0:
            prev = log.term_at_or_before(next_idx - 1)
            if prev is None and next_idx - 1 >= log.start_index:
                return None
            if prev is None and not div.snapshot_covers(next_idx - 1):
                prev = None  # empty log start
            elif prev is None:
                prev = div.snapshot_term_index(next_idx - 1)
                if prev is None:
                    return None
        if heartbeat:
            entries = ()
        else:
            entries = tuple(log.get_entries(next_idx, log.next_index,
                                            self.buffer_byte_limit))
        return AppendEntriesRequest(
            header=RaftRpcHeader(div.member_id.peer_id, self.follower.peer_id,
                                 div.group_id),
            leader_term=div.state.current_term,
            previous=prev,
            entries=entries,
            leader_commit=log.get_last_committed_index(),
            # cluster-wide commit picture piggyback (CommitInfoCache)
            commit_infos=div.get_commit_infos_wire(),
        )

    # -------------------------------------------------------------- window

    def _reset_window(self, *, rewind_to: Optional[int] = None,
                      backoff_s: float = 0.0) -> None:
        """Discard the pipeline: ignore everything in flight, rewind the send
        cursor (reference resetClient: follower.decreaseNextIndex + clear the
        request map)."""
        self._epoch += 1
        self._inflight = 0
        f = self.follower
        # NB: the rewind target is deliberately NOT floored at log.start_index
        # — next_index < start_index is exactly what routes _fill_window into
        # the snapshot-install path for a follower behind the purged log.
        if rewind_to is not None:
            target = max(rewind_to, 0)
            if target <= f.match_index:
                # The follower's INCONSISTENCY hint is authoritative: it has
                # lost entries past its recorded match (possible only with a
                # volatile log, e.g. memory-log restart) — regress the match
                # so commit quorum math stays honest.
                f.match_index = target - 1
                self.division.on_follower_match_regressed(f)
            f.next_index = target
        else:
            f.next_index = max(f.match_index + 1, 0)
        if backoff_s > 0:
            self._backoff_until = time.monotonic() + backoff_s
        self._wake.set()

    def _fill_window(self) -> None:
        """Issue batches until the window is full or the log is drained."""
        div = self.division
        log = div.state.log
        f = self.follower
        while (self._running and div.is_leader()
               and self._inflight < self.window_limit
               and not f.snapshot_in_progress):
            next_idx = f.next_index
            if next_idx >= log.next_index:
                return  # fully caught up (at send level)
            if not log.is_resident(next_idx):
                # evicted segment: fault it in off-loop, then resume — a
                # synchronous multi-MB read+decode here would stall every
                # division's heartbeats and election timers
                if not self._prefaulting:
                    self._prefaulting = True
                    self._spawn(self._prefault(next_idx))
                return
            request = self._build_request(next_idx)
            if request is None:
                # behind the purged log -> snapshot path, serialized by the
                # snapshot_in_progress flag inside try_install_snapshot
                self._spawn(self._install_snapshot())
                return
            if not request.entries:
                return
            f.next_index = request.entries[-1].index + 1
            self._inflight += 1
            self._last_send_s = time.monotonic()
            self._spawn(self._send(request, self._epoch, pipelined=True))

    def _spawn(self, coro) -> None:
        t = asyncio.create_task(coro)
        self._pending_sends.add(t)
        t.add_done_callback(self._pending_sends.discard)

    async def _install_snapshot(self) -> None:
        div = self.division
        handled = await div.try_install_snapshot(self.follower)
        if handled:
            self._wake.set()

    async def _prefault(self, index: int) -> None:
        try:
            await asyncio.to_thread(self.division.state.log.prefault, index)
        finally:
            self._prefaulting = False
        self._wake.set()

    async def _send(self, request: AppendEntriesRequest, epoch: int,
                    pipelined: bool, coalesce: bool = False) -> None:
        div = self.division
        try:
            if coalesce:
                # multi-raft heartbeat batching: one RPC per destination
                # server per window, carrying every group's heartbeat
                reply = await div.server.heartbeats.submit(
                    self.follower.peer_id, request)
            else:
                reply = await div.server.send_server_rpc(
                    self.follower.peer_id, request)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if epoch == self._epoch and self._running:
                # Connection trouble: drop the pipeline, retry after a pause
                # paced by the heartbeat timer (GrpcLogAppender.onError).
                # Log (rate-limited) — a silent persistent error here looks
                # like a wedged follower with no trace of why.
                now = time.monotonic()
                if now - self._last_error_log_s > 2.0:
                    self._last_error_log_s = now
                    LOG.warning("%s -> %s append failed (epoch %d): %s",
                                self.division.member_id,
                                self.follower.peer_id, self._epoch, e)
                self._reset_window(backoff_s=self.heartbeat_interval_s)
            return
        if epoch != self._epoch or not self._running:
            return  # window was reset while this was in flight
        if pipelined:
            self._inflight -= 1
        await self._on_reply(request, reply, epoch)
        self._wake.set()

    async def _on_reply(self, request: AppendEntriesRequest,
                        reply: AppendEntriesReply, epoch: int) -> None:
        div = self.division
        if reply.term > div.state.current_term:
            await div.change_to_follower(reply.term, leader_id=None,
                                         reason="higher term in append reply")
            return
        if reply.result == AppendResult.SUCCESS:
            self.follower.commit_index = max(self.follower.commit_index,
                                             reply.follower_commit)
            div.update_commit_info(self.follower.peer_id,
                                   reply.follower_commit)
            # Cap the confirmed match at what THIS request actually verified
            # against our log (prev check + entries sent).  The follower's
            # raw flush_index may cover a stale tail from a previous term
            # that a heartbeat never examined; counting it toward quorum
            # could commit entries that are not truly replicated.
            last_covered = (request.entries[-1].index if request.entries
                            else (request.previous.index if request.previous
                                  else -1))
            confirmed = min(reply.match_index, last_covered)
            if self.follower.update_match(confirmed):
                div.on_follower_ack(self.follower)
            else:
                div.on_follower_heartbeat_ack(self.follower)
        elif reply.result == AppendResult.INCONSISTENCY:
            if epoch == self._epoch:
                hint = min(reply.next_index,
                           max(request.previous.index if request.previous
                               else 0, 0))
                f = self.follower
                if hint <= f.match_index and (
                        request.previous is None
                        or request.previous.index != f.match_index):
                    # Heartbeats travel unary/coalesced while entry appends
                    # ride the ordered stream, so a stale heartbeat's
                    # INCONSISTENCY can land after a newer SUCCESS raised
                    # match in the same epoch.  This request never examined
                    # our recorded match position, so its rejection is not
                    # authoritative for a regress: reset the window and
                    # re-probe at the match instead.  A genuine volatile-log
                    # restart fails the probe (previous.index == match) too
                    # and regresses then, via the authoritative branch.
                    self._reset_window()
                else:
                    self._reset_window(rewind_to=hint)
        elif reply.result == AppendResult.NOT_LEADER:
            # stale term on our side already handled above; otherwise ignore
            pass

    # --------------------------------------------------------------- loops

    async def _run(self) -> None:
        div = self.division
        # Initial empty append: announces leadership and probes the follower
        # log position right away (the reference appender sends immediately
        # on start; followers learn leader identity from this probe).
        probe = self._build_request(self.follower.next_index, heartbeat=True)
        if probe is not None:
            self._last_send_s = time.monotonic()
            self._spawn(self._send(probe, self._epoch, pipelined=False))
        while self._running and div.is_leader():
            now = time.monotonic()
            if now < self._backoff_until:
                await asyncio.sleep(self._backoff_until - now)
                continue
            self._wake.clear()
            self._fill_window()
            # Plain wait, no per-iteration wait_for timer: every completion
            # path sets _wake (replies, errors via window reset, prefaults,
            # snapshot installs), and the heartbeat loop doubles as the
            # periodic waker so fills retry at least once per interval.
            await self._wake.wait()

    def on_heartbeat_sweep(self, now: float) -> None:
        """One iteration of the dedicated heartbeat channel, driven by the
        SERVER-level sweep (server.HeartbeatScheduler) instead of a task per
        (division, follower) — at thousands of co-hosted groups, 2G standing
        timer tasks were the scaling wall, and the sweep phase-aligns all
        heartbeats toward a destination so coalescing folds them into one
        RPC.  Semantics match the per-appender loop it replaces: an empty
        AppendEntries goes out whenever nothing else has been sent for an
        interval, regardless of window occupancy (GrpcLogAppender.java:172
        heartbeat stream)."""
        div = self.division
        if not self._running or not div.is_leader():
            return
        self._wake.set()  # periodic fill retry for the main loop
        try:
            div.check_follower_slowness(self.follower)
            if now - self._last_send_s < self.heartbeat_interval_s * 0.9:
                return  # recent traffic doubles as a heartbeat
            if now < self._backoff_until:
                return
            hb = self._build_request(self.follower.next_index,
                                     heartbeat=True)
            if hb is None:
                return  # snapshot path owns this follower right now
            self._last_send_s = now
            self._spawn(self._send(hb, self._epoch, pipelined=False,
                                   coalesce=div.server.heartbeat_coalescing))
        except Exception:
            # the sweep must never die on one follower's error — the wake
            # above already ran, so fills keep retrying regardless
            LOG.exception("%s heartbeat sweep iteration failed",
                          self.division.member_id)


class LeaderContext:
    """Everything that exists only while this division leads
    (reference LeaderStateImpl minus the event thread)."""

    def __init__(self, division, properties=None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        self.division = division
        p = division.server.properties
        self.pending = PendingRequests(
            RaftServerConfigKeys.Write.element_limit(p),
            RaftServerConfigKeys.Write.byte_limit(p))
        self.followers: dict[RaftPeerId, FollowerInfo] = {}
        self.appenders: dict[RaftPeerId, LogAppender] = {}
        self.startup_index: int = -1  # the conf entry appended on election
        self.leader_ready = asyncio.get_event_loop().create_future()
        # shared with the server-level HeartbeatScheduler sweep — the two
        # cadences must agree or heartbeat gaps silently grow
        self._heartbeat_interval_s = division.server.heartbeat_interval_s
        self._buffer_byte_limit = \
            RaftServerConfigKeys.Log.Appender.buffer_byte_limit(p)
        self._window_limit = \
            RaftServerConfigKeys.Log.Appender.pipeline_window(p)
        from ratis_tpu.metrics import LogAppenderMetrics
        self.appender_metrics = LogAppenderMetrics(division.member_id)

    def start_appenders(self) -> None:
        div = self.division
        next_index = div.state.log.next_index
        for peer in div.state.configuration.all_peers():
            if peer.id == div.member_id.peer_id:
                continue
            self.add_follower(peer.id, next_index)

    def add_follower(self, peer_id: RaftPeerId, next_index: int) -> None:
        if peer_id in self.followers:
            return
        info = FollowerInfo(peer_id, next_index)
        self.followers[peer_id] = info
        appender = LogAppender(self.division, info, self._heartbeat_interval_s,
                               self._buffer_byte_limit, self._window_limit)
        self.appenders[peer_id] = appender
        self.appender_metrics.add_follower_gauges(
            peer_id, lambda i=info: i.next_index,
            lambda i=info: i.match_index,
            lambda i=info: time.monotonic() - i.last_rpc_response_s)
        appender.start()

    async def remove_follower(self, peer_id: RaftPeerId) -> None:
        self.followers.pop(peer_id, None)
        self.appender_metrics.remove_follower_gauges(peer_id)
        a = self.appenders.pop(peer_id, None)
        if a is not None:
            await a.stop()

    def notify_appenders(self) -> None:
        for a in self.appenders.values():
            a.notify()

    async def stop(self, exception: Optional[NotLeaderException] = None) -> None:
        for a in list(self.appenders.values()):
            await a.stop()
        self.appenders.clear()
        self.appender_metrics.unregister()
        if exception is not None:
            # StateMachine.notifyNotLeader (StateMachine.java:241): the SM
            # sees the client requests that will never commit here, before
            # their futures fail with NotLeaderException.
            pending_reqs = self.pending.requests()
            if pending_reqs:
                try:
                    await self.division.state_machine.notify_not_leader(
                        pending_reqs)
                except Exception:
                    LOG.exception("%s notify_not_leader raised",
                                  self.division.member_id)
            self.pending.drain_not_leader(exception)
        if not self.leader_ready.done():
            self.leader_ready.cancel()
