"""Linearizable reads: readIndex protocol, leader lease, read-after-write.

Capability parity with the reference read stack:
- ReadIndexHeartbeats (ratis-server/.../impl/ReadIndexHeartbeats.java:40):
  readIndex = leader commitIndex, leadership confirmed by a majority-ack
  heartbeat round before serving (Raft §6.4).
- LeaderLease (LeaderLease.java:36): skip the heartbeat round while
  now < majority-ack-time + ratio*electionTimeout (the lease math runs in
  ops.quorum.lease_expiry / ops.reference.lease_expiry).
- ReadRequests (ReadRequests.java:35): appliedIndex -> futures completed by
  the apply loop once the state machine reaches the readIndex.
- WriteIndexCache (WriteIndexCache.java): clientId -> last write index for
  read-after-write-consistent reads.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Optional


class AppliedIndexWaiters:
    """appliedIndex -> futures; the apply loop advances the frontier."""

    def __init__(self):
        self.heap: list[tuple[int, int, asyncio.Future]] = []
        self._seq = 0
        self.applied = -1

    async def wait_applied(self, index: int, timeout_s: float) -> int:
        if index <= self.applied:
            return self.applied
        fut = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self.heap, (index, self._seq, fut))
        return await asyncio.wait_for(fut, timeout_s)

    def advance(self, applied: int) -> None:
        if applied <= self.applied:
            return
        self.applied = applied
        while self.heap and self.heap[0][0] <= applied:
            _, _, fut = heapq.heappop(self.heap)
            if not fut.done():
                fut.set_result(applied)


class WriteIndexCache:
    """clientId -> latest write log index (expiring)."""

    def __init__(self, expiry_s: float = 60.0):
        self._map: dict[bytes, tuple[int, float]] = {}
        self.expiry_s = expiry_s

    def put(self, client_id: bytes, index: int) -> None:
        self._map[client_id] = (index, time.monotonic())

    def get(self, client_id: bytes) -> int:
        v = self._map.get(client_id)
        if v is None:
            return -1
        index, t = v
        if (time.monotonic() - t) > self.expiry_s:
            del self._map[client_id]
            return -1
        return index

    def __len__(self) -> int:
        return len(self._map)

    def sweep(self, now: Optional[float] = None) -> int:
        """Drop every expired entry (the lazy ``get`` path only evicts
        keys that are queried again — a fleet of transient client ids
        would otherwise accrete one entry each, forever).  Called from
        the apply loop's slow tick; returns the number evicted."""
        if now is None:
            now = time.monotonic()
        dead = [cid for cid, (_, t) in self._map.items()
                if (now - t) > self.expiry_s]
        for cid in dead:
            del self._map[cid]
        return len(dead)

    def next_expiry_s(self) -> float:
        """Oldest entry's expiry time (upkeep-plane CH_CACHE waterline);
        +inf when empty.  O(n) only when the waterline fires."""
        if not self._map:
            return float("inf")
        return min(t for _, t in self._map.values()) + self.expiry_s


class LeaseState:
    """Host mirror of the lease decision; the expiry itself comes from the
    quorum engine's last-ack majority math."""

    def __init__(self, enabled: bool, ratio: float, election_timeout_ms: float):
        self.enabled = enabled
        self.lease_ms = ratio * election_timeout_ms

    def is_valid(self, now_ms: int, lease_expiry_ms: int) -> bool:
        return self.enabled and now_ms < lease_expiry_ms


class ReadSteering:
    """Per-server readIndex steering table (the placement actuator's
    lease/read hook): peer name -> monotonic avoid-until expiry.  The
    batched confirmation sweep deprioritizes the listed peers as
    confirmation targets — per group, only when enough unsteered voters
    remain to still reach majority, so a steered peer is never traded
    for availability.  Always constructed (empty-dict checks are free);
    only the placement actuator ever populates it."""

    def __init__(self):
        self._avoid: dict[str, float] = {}
        self.steered = 0  # confirmation sends skipped off steered peers

    def steer(self, peer: str, ttl_s: float,
              now: Optional[float] = None) -> bool:
        """Avoid ``peer`` for ``ttl_s``; True only when this opens a NEW
        steering episode (renewals extend silently — the actuator
        journals/counts per episode, not per policy round)."""
        if now is None:
            now = time.monotonic()
        fresh = self._avoid.get(peer, 0.0) <= now
        self._avoid[peer] = now + max(0.0, ttl_s)
        return fresh

    def clear(self, peer: str) -> None:
        self._avoid.pop(peer, None)

    def avoided(self, now: Optional[float] = None) -> set:
        """Currently-steered peer names (expired entries pruned)."""
        if not self._avoid:
            return set()
        if now is None:
            now = time.monotonic()
        dead = [p for p, t in self._avoid.items() if t <= now]
        for p in dead:
            del self._avoid[p]
        return set(self._avoid)
