"""MessageStream: accumulate a chunked Message into one log entry.

Capability parity with the reference MessageStreamApi server side
(ratis-server/src/main/java/org/apache/ratis/server/impl/MessageStreamRequests.java,
RaftServerImpl.messageStreamAsync:1111): a client splits one large Message
into sub-requests sharing a ``stream_id`` with increasing ``message_id``;
the server appends each chunk in order and, on ``end_of_request``, replays
the assembled bytes through the normal write path as a single transaction.
Long-payload scaling analog of sequence parallelism (SURVEY.md §2.9).

Retry semantics (the client's failover loop re-sends a chunk whose reply
was lost): a duplicate of the *last* appended chunk is acked as a no-op,
and a retried end-of-request for an already-assembled stream is answered
from the retry cache keyed by the write's (clientId, callId) — see
``RETIRED`` handling in Division._message_stream_async.  Streams idle
longer than ``expiry_s`` are lazily reclaimed so an abandoned client
cannot pin the byte budget forever.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, Optional, Tuple

from ratis_tpu.protocol.exceptions import StreamException
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.requests import (RaftClientRequest,
                                         write_request_type)

Key = Tuple[bytes, int]  # (clientId, streamId)


class _PendingStream:
    """One in-flight stream (reference PendingStream): ordered chunks."""

    __slots__ = ("stream_id", "next_id", "chunks", "touched_s")

    def __init__(self, stream_id: int) -> None:
        self.stream_id = stream_id
        self.next_id = 0
        self.chunks: list[bytes] = []
        self.touched_s = time.monotonic()

    def is_duplicate(self, message_id: int, message: Message) -> bool:
        """A re-sent copy of the chunk we appended last (reply was lost)."""
        return (message_id == self.next_id - 1 and self.chunks
                and self.chunks[-1] == message.content)

    def append(self, message_id: int, message: Message) -> None:
        if message_id != self.next_id:
            raise StreamException(
                f"stream {self.stream_id}: out-of-order chunk "
                f"{message_id}, expected {self.next_id}")
        self.chunks.append(message.content)
        self.next_id += 1
        self.touched_s = time.monotonic()

    @property
    def size(self) -> int:
        return sum(len(c) for c in self.chunks)

    def assemble(self) -> Message:
        return Message(b"".join(self.chunks))


class MessageStreamRequests:
    """Per-division registry of pending streams keyed by (clientId, streamId).

    ``stream_end_of_request_async`` returns either the assembled WRITE
    request or :data:`RETIRED` when this (stream, call id) already
    assembled — the caller must then answer from the retry cache.
    """

    RETIRED = object()
    MAX_RETIRED = 4096

    def __init__(self, byte_limit: int = 64 << 20,
                 expiry_s: float = 300.0) -> None:
        self._streams: Dict[Key, _PendingStream] = {}
        self._retired: Deque[Tuple[Key, int]] = collections.deque(
            maxlen=self.MAX_RETIRED)  # (key, end-of-request callId)
        self._byte_limit = byte_limit
        self._expiry_s = expiry_s
        self._bytes = 0

    # -------------------------------------------------------------- chunks

    def _check_and_account(self, stream: Optional[_PendingStream],
                           key: Key, size: int) -> None:
        if self._bytes + size > self._byte_limit:
            if stream is not None:
                self._drop(key)
            raise StreamException(
                f"stream {key[1]}: byte limit {self._byte_limit} exceeded")
        self._bytes += size

    def stream_async(self, request: RaftClientRequest) -> None:
        """Append a non-final chunk; duplicate last chunks are acked no-op;
        raises StreamException on true disorder."""
        self._expire_idle()
        t = request.type
        key = (request.client_id.to_bytes(), t.stream_id)
        stream = self._streams.get(key)
        if stream is None:
            stream = _PendingStream(t.stream_id)
            self._streams[key] = stream
        if stream.is_duplicate(t.message_id, request.message):
            stream.touched_s = time.monotonic()
            return
        self._check_and_account(stream, key, len(request.message.content))
        try:
            stream.append(t.message_id, request.message)
        except StreamException:
            self._bytes -= len(request.message.content)
            self._drop(key)
            raise

    def stream_end_of_request_async(self, request: RaftClientRequest):
        """Final chunk: returns the assembled WRITE request (same client id +
        call id, so the retry cache dedupes normally), or :data:`RETIRED`
        for a re-sent end-of-request whose stream already assembled."""
        self._expire_idle()
        t = request.type
        key = (request.client_id.to_bytes(), t.stream_id)
        stream = self._streams.get(key)
        if stream is None:
            if (key, request.call_id) in self._retired:
                return self.RETIRED
            if t.message_id != 0:
                raise StreamException(
                    f"stream {t.stream_id}: unknown stream for final chunk "
                    f"{t.message_id} (lost to failover? restart the stream)")
            stream = _PendingStream(t.stream_id)
            self._streams[key] = stream
        if not stream.is_duplicate(t.message_id, request.message):
            self._check_and_account(stream, key,
                                    len(request.message.content))
            try:
                stream.append(t.message_id, request.message)
            except StreamException:
                self._bytes -= len(request.message.content)
                self._drop(key)
                raise
        message = stream.assemble()
        self._drop(key)
        self._retired.append((key, request.call_id))
        return RaftClientRequest(
            request.client_id, request.server_id, request.group_id,
            request.call_id, message, type=write_request_type(),
            timeout_ms=request.timeout_ms,
            replied_call_ids=request.replied_call_ids)

    # ----------------------------------------------------------- lifecycle

    def _drop(self, key: Key) -> None:
        stream = self._streams.pop(key, None)
        if stream is not None:
            self._bytes -= stream.size

    def _expire_idle(self) -> None:
        if self._expiry_s <= 0:
            return
        deadline = time.monotonic() - self._expiry_s
        for key in [k for k, s in self._streams.items()
                    if s.touched_s < deadline]:
            self._drop(key)

    def clear(self) -> None:
        self._streams.clear()
        self._retired.clear()
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._streams)

    @property
    def pending_bytes(self) -> int:
        return self._bytes
