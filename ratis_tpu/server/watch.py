"""Watch requests: futures resolved when an index reaches a replication level.

Capability parity with the reference WatchRequests
(ratis-server/.../impl/WatchRequests.java:42): per-level queues keyed by the
watched index, resolved when that level's frontier passes the index, failed
with NotReplicatedException on timeout (:185) and drained on step-down.

Levels (Raft.proto ReplicationLevel):
- MAJORITY:            leader commitIndex         >= watched index
- ALL:                 min over peers' matchIndex >= watched index
- MAJORITY_COMMITTED:  majority-min over peers' commitIndex >= index
- ALL_COMMITTED:       min over peers' commitIndex >= index
The frontiers are computed by the division from engine state + follower
commit infos piggybacked on AppendEntries replies.
"""

from __future__ import annotations

import asyncio
import heapq
from typing import Optional

from ratis_tpu.protocol.exceptions import NotReplicatedException
from ratis_tpu.protocol.requests import ReplicationLevel


class _Queue:
    """Min-heap of (index, future) for one replication level."""

    def __init__(self, level: ReplicationLevel):
        self.level = level
        self.heap: list[tuple[int, int, asyncio.Future]] = []
        self._seq = 0
        self.frontier = -1

    def add(self, index: int) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        if index <= self.frontier:
            fut.set_result(self.frontier)
            return fut
        self._seq += 1
        heapq.heappush(self.heap, (index, self._seq, fut))
        return fut

    def update(self, new_frontier: int) -> int:
        if new_frontier <= self.frontier:
            return 0
        self.frontier = new_frontier
        n = 0
        while self.heap and self.heap[0][0] <= new_frontier:
            _, _, fut = heapq.heappop(self.heap)
            if not fut.done():
                fut.set_result(new_frontier)
                n += 1
        return n

    def drain(self, exc: Exception) -> None:
        while self.heap:
            _, _, fut = heapq.heappop(self.heap)
            if not fut.done():
                fut.set_exception(exc)


class WatchRequests:
    def __init__(self, timeout_s: float = 10.0, element_limit: int = 65536):
        self.queues = {lvl: _Queue(lvl) for lvl in ReplicationLevel}
        self.timeout_s = timeout_s
        self.element_limit = element_limit

    def pending_count(self) -> int:
        return sum(len(q.heap) for q in self.queues.values())

    async def watch(self, index: int, level: ReplicationLevel,
                    call_id: int = 0) -> int:
        from ratis_tpu.protocol.exceptions import ResourceUnavailableException
        if self.pending_count() >= self.element_limit:
            raise ResourceUnavailableException(
                f"too many pending watch requests ({self.element_limit})")
        fut = self.queues[level].add(index)
        try:
            return await asyncio.wait_for(fut, self.timeout_s)
        except asyncio.TimeoutError:
            raise NotReplicatedException(call_id, level, index) from None

    def update(self, level: ReplicationLevel, new_frontier: int) -> int:
        return self.queues[level].update(new_frontier)

    def update_all_levels(self, majority_commit: int, all_match: int,
                          majority_committed: int, all_committed: int) -> None:
        self.update(ReplicationLevel.MAJORITY, majority_commit)
        self.update(ReplicationLevel.ALL, all_match)
        self.update(ReplicationLevel.MAJORITY_COMMITTED, majority_committed)
        self.update(ReplicationLevel.ALL_COMMITTED, all_committed)

    def drain(self, exc: Exception) -> None:
        for q in self.queues.values():
            q.drain(exc)

    def reset_frontiers(self) -> None:
        """New leadership term: stale frontiers from a previous term must not
        instantly satisfy watches the CURRENT follower set hasn't reached."""
        for q in self.queues.values():
            q.frontier = -1
