"""Per-server event-loop sharding (``raft.tpu.server.loop-shards``).

The traced host-path decomposition (docs/perf.md, round 6) located the
dominant north-star residual in single-event-loop queueing: at 5-peer x
10240 groups the server-side stage tiling sums to ~25-30ms of a 138ms
client p50 — the rest is ready-callback backlog on ONE saturated loop.
That made loop count a deployment shape; this module makes the shape
real: a :class:`LoopShardPool` runs N worker event loops (shard 0 is the
loop the server started on; shards 1..N-1 run in daemon threads), and the
server hash-pins every Division — and with it that division's request
handling, appenders, heartbeat sweep share, upkeep-plane slot
(server/upkeep.py: the packed deadline arrays the shard's sweep scans
are owned by the shard's loop, so registration, arming, and the
vectorized due-scan never cross threads), and outbound transport
connections — to one shard.

No reference analog maps 1:1 (the reference is thread-per-division on a
shared Netty event-loop group); the closest shape is Netty's
``NioEventLoopGroup``: a fixed pool of loops with channels pinned at
registration.  Cross-shard handoff uses ``run_coroutine_threadsafe``
wrapped back into the calling loop; with ``loop-shards=1`` (the default)
the pool is never constructed and every code path is the unsharded one.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import zlib
from typing import Optional

LOG = logging.getLogger(__name__)


def loop_ready_depth(loop: Optional[asyncio.AbstractEventLoop]) -> int:
    """Best-effort ready-callback backlog of ``loop`` — the queueing the
    traced decomposition blamed for the north-star residual, now a live
    introspection signal (/divisions shardQueueDepth).  CPython's event
    loop keeps its ready queue in ``_ready``; a loop implementation
    without one reports -1 (unknown), never raises."""
    if loop is None:
        return -1
    ready = getattr(loop, "_ready", None)
    if ready is None:
        return -1
    try:
        return len(ready)
    except Exception:
        return -1


class LoopShardPool:
    """N event loops; shard 0 is the caller's (primary) loop, the rest run
    ``run_forever`` on daemon threads until :meth:`close`."""

    def __init__(self, name: str, shards: int):
        self.name = name
        self.n = max(1, int(shards))
        self._loops: list[asyncio.AbstractEventLoop] = []
        self._threads: list[threading.Thread] = []
        self.started = False

    def start(self) -> None:
        """Spawn the worker loops.  Must run inside the primary loop (it
        becomes shard 0)."""
        if self.started:
            return
        self._loops = [asyncio.get_running_loop()]
        for i in range(1, self.n):
            ready = threading.Event()
            holder: dict = {}

            def _run(holder=holder, ready=ready) -> None:
                loop = asyncio.new_event_loop()
                holder["loop"] = loop
                asyncio.set_event_loop(loop)
                ready.set()
                try:
                    loop.run_forever()
                finally:
                    # cancel whatever close() could not unwind, then close
                    for task in asyncio.all_tasks(loop):
                        task.cancel()
                    try:
                        loop.run_until_complete(loop.shutdown_asyncgens())
                    except Exception:
                        pass
                    loop.close()

            t = threading.Thread(target=_run, name=f"{self.name}-shard{i}",
                                 daemon=True)
            t.start()
            ready.wait()
            self._loops.append(holder["loop"])
            self._threads.append(t)
        self.started = True

    # -- placement -----------------------------------------------------------

    def shard_of(self, key: bytes) -> int:
        """Stable hash-pin for a group id: same key -> same shard for the
        server's lifetime (division state is loop-affine)."""
        return zlib.crc32(key) % self.n

    def loop(self, idx: int) -> asyncio.AbstractEventLoop:
        return self._loops[idx]

    def queue_depth(self, idx: int) -> int:
        """Ready-callback backlog of shard ``idx``'s loop (-1 unknown)."""
        if not self.started or idx >= len(self._loops):
            return -1
        return loop_ready_depth(self._loops[idx])

    def queue_depths(self) -> list[int]:
        return [self.queue_depth(i) for i in range(self.n)]

    def loop_index(self, loop: Optional[asyncio.AbstractEventLoop] = None
                   ) -> int:
        """Shard index of ``loop`` (default: the running loop); -1 when the
        loop is not one of the pool's."""
        if loop is None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return -1
        for i, lp in enumerate(self._loops):
            if lp is loop:
                return i
        return -1

    # -- cross-loop execution ------------------------------------------------

    async def run_on(self, idx: int, coro):
        """Await ``coro`` on shard ``idx``'s loop from ANY pool loop.  On
        the owning loop this is a plain await (zero indirection — the
        unsharded fast path)."""
        target = self._loops[idx]
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        if target is current:
            return await coro
        cf = asyncio.run_coroutine_threadsafe(coro, target)
        return await asyncio.wrap_future(cf)

    def call_soon(self, idx: int, fn, *args) -> None:
        target = self._loops[idx]
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        if target is current:
            fn(*args)
        else:
            target.call_soon_threadsafe(fn, *args)

    # -- lifecycle -----------------------------------------------------------

    async def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop the worker loops and join their threads.  Callers must have
        already unwound shard-pinned work (divisions, senders): stopping a
        loop strands whatever is still scheduled on it."""
        if not self.started:
            return
        for loop in self._loops[1:]:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass  # already stopped
        for t in self._threads:
            await asyncio.to_thread(t.join, join_timeout_s)
            if t.is_alive():
                LOG.warning("%s: shard thread %s did not join in %.0fs",
                            self.name, t.name, join_timeout_s)
        self._threads.clear()
        self._loops = self._loops[:1]
        self.started = False
