"""RaftStorage: on-disk layout, lock, metadata, and conf files per division.

Capability parity with the reference storage layer
(ratis-server/.../storage/RaftStorageImpl.java, RaftStorageDirectoryImpl.java:40-98):

    <root>/<groupId-uuid>/
        in_use.lock              exclusive-use marker
        current/
            raft-meta            (term, votedFor) — atomic tmp+rename
            raft-meta.conf       latest committed RaftConfiguration entry
            log_<s>-<e>          closed log segments
            log_inprogress_<s>   the open segment
        sm/                      StateMachine snapshots
        tmp/                     staging (snapshot install, atomic writes)

Atomic writes follow the reference AtomicFileOutputStream (tmp + rename);
metadata is msgpack instead of the reference's java Properties text.
"""

from __future__ import annotations

import asyncio
import os
import pathlib
from typing import Optional

import msgpack

from ratis_tpu.protocol.exceptions import AlreadyClosedException, RaftException
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.server.state import MetadataIO


_TMP_IDS = __import__("itertools").count(1)


def atomic_write(path: pathlib.Path, data: bytes) -> None:
    """tmp + fsync + rename (reference AtomicFileOutputStream).  The tmp
    name is unique per call: two concurrent writers of the SAME target
    (mass step-downs persisting raft-meta from racing to_thread workers
    — found by the chaos campaign's leader-crash scenario at 1024
    groups) must degrade to last-rename-wins, not to one of them
    renaming the other's half-written (or already-consumed) tmp away."""
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}.{next(_TMP_IDS)}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class RaftStorageDirectory:
    META_FILE = "raft-meta"
    CONF_FILE = "raft-meta.conf"
    LOCK_FILE = "in_use.lock"

    def __init__(self, root: "str | pathlib.Path", group_id: RaftGroupId):
        self.root = pathlib.Path(root) / str(group_id.uuid)
        self.current = self.root / "current"
        self.sm_dir = self.root / "sm"
        self.tmp_dir = self.root / "tmp"
        self.group_id = group_id
        self._locked = False

    def format(self) -> None:
        for d in (self.current, self.sm_dir, self.tmp_dir):
            d.mkdir(parents=True, exist_ok=True)

    def lock(self) -> None:
        """Exclusive-use marker (reference in_use.lock).  Single-process
        protection: O_EXCL create; stale locks from crashed processes are
        reclaimed when the recorded pid is dead."""
        lock = self.root / self.LOCK_FILE
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
        except FileExistsError:
            try:
                pid = int(lock.read_text() or "0")
            except ValueError:
                pid = 0
            alive = False
            if pid > 0:
                if pid == os.getpid():
                    alive = True  # another division in THIS process holds it
                else:
                    try:
                        os.kill(pid, 0)
                        alive = True
                    except OSError:
                        alive = False
            if alive:
                raise RaftException(
                    f"storage {self.root} is locked by live pid {pid}")
            lock.write_text(str(os.getpid()))
        self._locked = True

    def unlock(self) -> None:
        if self._locked:
            (self.root / self.LOCK_FILE).unlink(missing_ok=True)
            self._locked = False

    # -- raft-meta ------------------------------------------------------------

    def persist_metadata(self, term: int, voted_for: Optional[RaftPeerId]) -> None:
        data = msgpack.packb({"t": term,
                              "v": None if voted_for is None else voted_for.id})
        atomic_write(self.current / self.META_FILE, data)

    def load_metadata(self) -> tuple[int, Optional[RaftPeerId]]:
        path = self.current / self.META_FILE
        if not path.exists():
            return 0, None
        d = msgpack.unpackb(path.read_bytes(), raw=False)
        v = d.get("v")
        return d.get("t", 0), None if v is None else RaftPeerId.value_of(v)

    # -- raft-meta.conf -------------------------------------------------------

    def persist_conf_entry(self, entry: LogEntry) -> None:
        atomic_write(self.current / self.CONF_FILE, entry.to_bytes())

    def load_conf_entry(self) -> Optional[LogEntry]:
        path = self.current / self.CONF_FILE
        if not path.exists():
            return None
        return LogEntry.from_bytes(path.read_bytes())

    def exists(self) -> bool:
        return self.current.exists()


class FileMetadataIO(MetadataIO):
    """ServerState's (term, votedFor) persistence over RaftStorageDirectory.
    The blocking fsync runs in a thread so the event loop never stalls.

    Persists SERIALIZE per division and never regress the on-disk term:
    a vote handler and an append handler can both drive a term update in
    the same burst, and with unserialized to_thread workers the OLDER
    term could land last on disk — a durable term regression that lets a
    restarted node double-vote (found by the chaos campaign's election
    storms)."""

    def __init__(self, directory: RaftStorageDirectory):
        self.directory = directory
        self._lock = asyncio.Lock()
        self._last_term = -1

    async def persist(self, term: int, voted_for: Optional[RaftPeerId]) -> None:
        async with self._lock:
            if term < self._last_term:
                return  # stale writer lost the race; newer term is on disk
            self._last_term = term
            await asyncio.to_thread(self.directory.persist_metadata, term,
                                    voted_for)

    async def load(self) -> tuple[int, Optional[RaftPeerId]]:
        return self.directory.load_metadata()


def scan_group_dirs(root: "str | pathlib.Path") -> list[RaftGroupId]:
    """Boot-time discovery of hosted groups (RaftServerProxy.initGroups:257)."""
    rootp = pathlib.Path(root)
    out = []
    if not rootp.exists():
        return out
    for child in rootp.iterdir():
        if not child.is_dir():
            continue
        try:
            gid = RaftGroupId.value_of(child.name)
        except ValueError:
            continue
        if (child / "current").exists():
            out.append(gid)
    return out
