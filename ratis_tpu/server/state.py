"""ServerState: the durable per-division consensus variables.

Capability parity with the reference ServerState
(ratis-server/.../impl/ServerState.java:61): currentTerm / votedFor /
leaderId (:82-92), metadata persistence (persistMetadata:248), vote grant
bookkeeping (grantVote:259), log initialization (initRaftLog:172 — memory vs
segmented), candidate-vs-mine log comparison (compareLog:350), and the
configuration history (ConfigurationManager).
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from ratis_tpu.protocol.group import RaftGroup, RaftGroupMemberId
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import INVALID_LOG_INDEX, INVALID_TERM, TermIndex
from ratis_tpu.server.config import RaftConfiguration
from ratis_tpu.server.log.base import RaftLog
from ratis_tpu.server.log.memory import MemoryRaftLog


class ConfigurationManager:
    """Index -> configuration history with truncate rollback
    (reference ConfigurationManager, ratis-server/.../impl/)."""

    def __init__(self, initial: RaftConfiguration):
        self._initial = initial
        self._history: dict[int, RaftConfiguration] = {}

    def add(self, conf: RaftConfiguration) -> None:
        self._history[conf.log_index] = conf

    def current(self) -> RaftConfiguration:
        if not self._history:
            return self._initial
        return self._history[max(self._history)]

    def truncate(self, index: int) -> None:
        """Drop confs at log indexes >= index (log truncation rollback)."""
        for k in [k for k in self._history if k >= index]:
            del self._history[k]


class ServerState:
    def __init__(self, member_id: RaftGroupMemberId, group: RaftGroup,
                 log: Optional[RaftLog] = None,
                 metadata_io: Optional["MetadataIO"] = None):
        self.member_id = member_id
        self.current_term = 0
        self.voted_for: Optional[RaftPeerId] = None
        self.leader_id: Optional[RaftPeerId] = None
        self.log: RaftLog = log or MemoryRaftLog(f"log-{member_id}")
        self.conf_manager = ConfigurationManager(
            RaftConfiguration.from_peers(group.peers, log_index=INVALID_LOG_INDEX))
        self._metadata_io = metadata_io
        # Index of the newest entry known flushed (leader self-slot input).
        self.last_applied = TermIndex.INITIAL_VALUE

    @property
    def configuration(self) -> RaftConfiguration:
        return self.conf_manager.current()

    # -- term / vote ---------------------------------------------------------

    async def persist_metadata(self) -> None:
        """Durably record (term, votedFor) BEFORE replying to a vote or
        accepting a higher term (ServerState.persistMetadata:248)."""
        if self._metadata_io is not None:
            await self._metadata_io.persist(self.current_term, self.voted_for)

    async def update_current_term(self, term: int) -> bool:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self.leader_id = None
            await self.persist_metadata()
            return True
        return False

    async def grant_vote(self, candidate: RaftPeerId) -> None:
        self.voted_for = candidate
        self.leader_id = None
        await self.persist_metadata()

    async def init_election_term(self) -> int:
        """Candidate entering a real election: term+1, vote self, persist."""
        self.current_term += 1
        self.voted_for = self.member_id.peer_id
        self.leader_id = None
        await self.persist_metadata()
        return self.current_term

    def set_leader(self, leader_id: Optional[RaftPeerId]) -> bool:
        changed = self.leader_id != leader_id
        self.leader_id = leader_id
        return changed

    # -- log comparison (ServerState.compareLog:350) -------------------------

    def is_log_up_to_date(self, candidate_last: TermIndex) -> bool:
        mine = self.log.get_last_entry_term_index()
        if mine is None:
            return True
        if candidate_last.term != mine.term:
            return candidate_last.term > mine.term
        return candidate_last.index >= mine.index

    # -- configuration -------------------------------------------------------

    def apply_log_entry_configuration(self, entry: LogEntry) -> None:
        if entry.is_config():
            self.conf_manager.add(RaftConfiguration.from_entry(entry))

    def truncate_configurations(self, index: int) -> None:
        self.conf_manager.truncate(index)


class MetadataIO:
    """Abstract (term, votedFor) persistence; storage milestone supplies the
    atomic-file implementation (cf. raft-meta,
    RaftStorageDirectoryImpl.java:41)."""

    async def persist(self, term: int, voted_for: Optional[RaftPeerId]) -> None:
        pass

    async def load(self) -> tuple[int, Optional[RaftPeerId]]:
        return 0, None
