"""UpkeepPlane: per-loop-shard vectorized host bookkeeping.

One plane per loop shard holds the packed ``[capacity, N_CHANNELS]``
deadline array (ops/upkeep.py) with one dense slot per registered
division.  The shard's heartbeat sweep then does ONE ``deadlines <= now``
compare + ``nonzero`` scan and dispatches only the due groups, instead of
walking every division it owns:

- CH_HEARTBEAT — the leader's next heartbeat due-time, min over appenders
  of max(last_ack + 0.9*hb, last_send + 0.45*hb); non-leaders hold +inf
  and cost nothing.  Armed conservatively EARLY: an early dispatch runs
  ``heartbeat_item`` which declines exactly as the legacy loop would, so
  an early deadline can never change behavior, only cost.
- CH_HIBERNATE — an asleep leader's backstop refresh clock (backstop/4);
  while asleep CH_HEARTBEAT is cleared, so the slot is touched a handful
  of times per minute instead of every sweep.
- CH_CACHE — oldest-expiry waterline over the division's retry cache and
  WriteIndexCache; an idle shard with empty caches does zero expiry work.
- CH_WINDOW — client-window idle sweep, armed only while windows exist.
- CH_WATCH — a dirty mark (0.0) set by ack paths; the sweep folds the
  per-ack ``_update_watch_frontiers`` calls into one per dirty slot.

Slot lifecycle reuses the engine ledger's generation-guard pattern
(engine/ledger.py): every (re)allocation bumps ``gen[slot]``, and every
write/clear validates the caller's generation, so a division removed and
replaced by another cannot fire stale deadlines into the new tenant.

Threading: a plane is owned by its shard's event loop — division
start/close and the sweep all run there (divisions are loop-affine), so
like the rest of the server there are no locks.  The ack paths that mark
CH_WATCH dirty also run on the division's own loop.

Everything here is gated behind ``raft.tpu.upkeep.enabled``; unset, no
plane exists and every caller falls through to the per-group legacy path
bit-for-bit.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Optional

import numpy as np

from ratis_tpu.ops import upkeep as ops
from ratis_tpu.ops.upkeep import (CH_CACHE, CH_HEARTBEAT, CH_HIBERNATE,
                                  CH_WATCH, CH_WINDOW, N_CHANNELS,
                                  NO_DEADLINE)

if TYPE_CHECKING:
    from ratis_tpu.server.division import Division

LOG = logging.getLogger(__name__)

_INITIAL_CAPACITY = 64


class UpkeepPlane:
    """Dense per-group deadline slots for one loop shard."""

    def __init__(self, server, shard: int = 0):
        self.server = server
        self.shard = shard
        self._cap = _INITIAL_CAPACITY
        self.deadlines = ops.new_deadlines(self._cap)
        # per-slot min over channels, kept current on every write: the
        # sweep scans THIS [cap] vector, not the [cap, 5] matrix, so the
        # per-tick cost is dominated by fixed numpy overhead (ops/upkeep
        # due_scan_min), not by element count
        self.row_min = np.full(self._cap, NO_DEADLINE, dtype=np.float64)
        # generation guard (engine/ledger.py pattern): bumped on every
        # allocation; stale (slot, gen) writes are dropped.
        self.gen = np.zeros(self._cap, dtype=np.int64)
        self._divisions: list[Optional["Division"]] = [None] * self._cap
        self._free: list[int] = list(range(self._cap - 1, -1, -1))
        self.registered = 0
        # sweep-cost observability (metrics registered by the server once
        # per plane under the `upkeep_plane` registry)
        self.sweeps = 0
        self.idle_skips = 0
        self.last_due = 0
        self._timer = None
        self._idle_counter = None

    # ------------------------------------------------------------ lifecycle

    def _grow(self) -> None:
        new_cap = self._cap * 2
        grown = ops.new_deadlines(new_cap)
        grown[:self._cap] = self.deadlines
        self.deadlines = grown
        row_min = np.full(new_cap, NO_DEADLINE, dtype=np.float64)
        row_min[:self._cap] = self.row_min
        self.row_min = row_min
        gen = np.zeros(new_cap, dtype=np.int64)
        gen[:self._cap] = self.gen
        self.gen = gen
        self._divisions.extend([None] * (new_cap - self._cap))
        self._free.extend(range(new_cap - 1, self._cap - 1, -1))
        self._cap = new_cap

    def register(self, div: "Division") -> tuple[int, int]:
        """Allocate a slot for a starting division; all channels unarmed."""
        if not self._free:
            self._grow()
        slot = self._free.pop()
        self.gen[slot] += 1
        self.deadlines[slot, :] = NO_DEADLINE
        self.row_min[slot] = NO_DEADLINE
        self._divisions[slot] = div
        self.registered += 1
        return slot, int(self.gen[slot])

    def unregister(self, slot: int, gen: int) -> None:
        if not self._valid(slot, gen):
            return
        self.gen[slot] += 1  # invalidate outstanding (slot, gen) handles
        self.deadlines[slot, :] = NO_DEADLINE
        self.row_min[slot] = NO_DEADLINE
        self._divisions[slot] = None
        self._free.append(slot)
        self.registered -= 1

    def _valid(self, slot: int, gen: int) -> bool:
        return 0 <= slot < self._cap and self.gen[slot] == gen \
            and self._divisions[slot] is not None

    def division_at(self, slot: int) -> Optional["Division"]:
        return self._divisions[slot]

    # ------------------------------------------------------------- deadlines

    def set_deadline(self, slot: int, gen: int, channel: int,
                     when: float) -> None:
        if self._valid(slot, gen):
            self.deadlines[slot, channel] = when
            self.row_min[slot] = self.deadlines[slot].min()

    def clear(self, slot: int, gen: int, channel: int) -> None:
        if self._valid(slot, gen):
            self.deadlines[slot, channel] = NO_DEADLINE
            self.row_min[slot] = self.deadlines[slot].min()

    def mark_watch_dirty(self, slot: int, gen: int) -> None:
        """O(1) store from the ack paths; folded into the next sweep."""
        if self._valid(slot, gen):
            self.deadlines[slot, CH_WATCH] = 0.0
            self.row_min[slot] = self.deadlines[slot].min()

    def is_armed(self, slot: int, gen: int, channel: int) -> bool:
        return self._valid(slot, gen) \
            and self.deadlines[slot, channel] != NO_DEADLINE

    # ----------------------------------------------------------------- sweep

    def sweep(self, now: float) -> tuple[np.ndarray, np.ndarray]:
        """One vectorized scan: returns (due_slots, due_mask) where
        due_mask is [len(due_slots), N_CHANNELS].  The caller dispatches;
        the caller also re-arms (dispatch outcomes decide the next due)."""
        self.sweeps += 1
        slots = ops.due_scan_min(self.row_min, now)
        self.last_due = len(slots)
        if len(slots) == 0:
            self.idle_skips += 1
            if self._idle_counter is not None:
                self._idle_counter.inc()
            return slots, np.zeros((0, N_CHANNELS), dtype=bool)
        return slots, ops.due_channels(self.deadlines, slots, now)


def create_planes(server) -> list[UpkeepPlane]:
    """One plane per loop shard (a single plane when unsharded)."""
    n = server.loop_shards if server.shards is not None else 1
    return [UpkeepPlane(server, shard=i) for i in range(n)]
