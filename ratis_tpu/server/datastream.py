"""DataStream server side: receive bulk bytes, fan out, link at apply.

Capability parity with the reference DataStream server
(ratis-netty/src/main/java/org/apache/ratis/netty/server/DataStreamManagement.java:85
+ NettyServerStreamRpc): the *primary* peer (the one the client connected
to) opens a local DataChannel via ``StateMachine.data_stream``, forwards
every packet to its successors per the stream's RoutingTable
(getSuccessors:196), and on CLOSE — once the local channel is forced and
every successor acked — submits the header RaftClientRequest through the
ordinary consensus path; at apply each receiving peer ``data_link``s its
streamed bytes to the committed entry (FileStoreStateMachine.java:196-216).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional, Tuple

from ratis_tpu.metrics import DataStreamMetrics
from ratis_tpu.protocol.exceptions import DataStreamException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.requests import RaftClientRequest, RequestType
from ratis_tpu.protocol.routing import RoutingTable
from ratis_tpu.transport.datastream import (FLAG_CLOSE, FLAG_PRIMARY,
                                            FLAG_SUCCESS, FLAG_SYNC,
                                            KIND_DATA, KIND_HEADER,
                                            KIND_REPLY, DataStreamConnection,
                                            DataStreamServer, Packet,
                                            PeerConnection, decode_header,
                                            encode_header)

LOG = logging.getLogger(__name__)

LinkKey = Tuple[bytes, int]  # (clientId, callId) of the header request


def _consume_result(fut: asyncio.Future) -> None:
    """Retrieve an abandoned ack future's outcome so the loop never logs
    'exception never retrieved' for a failure path we already handled."""
    if not fut.cancelled():
        fut.exception()


class StreamInfo:
    """One receiving stream on one peer (reference StreamInfo:88-193)."""

    def __init__(self, request: RaftClientRequest, is_primary: bool,
                 local, remotes: "list[_RemoteStream]") -> None:
        self.request = request
        self.is_primary = is_primary
        self.local = local            # StateMachine DataStream | None
        self.remotes = remotes
        self.next_offset = 0
        self.bytes_written = 0
        self.closed = False
        self.touched_s = time.monotonic()
        # in-flight packet completions (successor acks being awaited while
        # later packets already write — the pipeline); CLOSE drains these
        self.pending: set[asyncio.Task] = set()
        self.failed: Optional[Exception] = None
        # loop shard owning this stream's handling (the owning division's
        # shard when the plane is shard-pinned; None = primary loop) —
        # cleanup must unwind the stream's tasks/connections on this loop
        self.shard: Optional[int] = None


class _RemoteStream:
    """Forwarding leg to one successor (reference RemoteStream)."""

    def __init__(self, peer_id: RaftPeerId, address: str, tls=None) -> None:
        self.peer_id = peer_id
        self.address = address
        self.conn = DataStreamConnection(address, tls=tls)

    async def connect(self) -> None:
        await self.conn.connect()

    async def forward(self, packet: Packet) -> Packet:
        """Forward and await the successor's ack."""
        reply = await (await self.send(packet))
        if not reply.success:
            raise DataStreamException(
                f"successor {self.peer_id} rejected stream "
                f"{packet.stream_id} offset {packet.offset}")
        return reply

    async def send(self, packet: Packet) -> "asyncio.Future[Packet]":
        """Put the packet on the successor's socket NOW (ordered per
        connection) and return the ack future — the pipelined half of
        :meth:`forward`."""
        return await self.conn.send(packet)

    async def close(self) -> None:
        await self.conn.close()


class DataStreamManagement:
    """Per-server packet handler + the apply-time link registry."""

    def __init__(self, server, address: str,
                 expiry_s: float = 300.0) -> None:
        self.server = server  # RaftServer
        from ratis_tpu.conf.keys import NettyConfigKeys
        self.tls = NettyConfigKeys.DataStreamTls.tls_config(
            server.properties)
        self.transport = DataStreamServer(address, self._on_packet,
                                          tls=self.tls)
        # streamId -> StreamInfo while streaming (ids are client-random
        # 64-bit, collision-free in practice)
        self._streams: Dict[int, StreamInfo] = {}
        # (clientId, callId) -> (StreamInfo, retired-at) awaiting apply-time
        # link; swept together with idle streams so an aborted submit can't
        # pin temp files/FDs on followers forever
        self._links: Dict[LinkKey, Tuple[StreamInfo, float]] = {}
        self._expiry_s = expiry_s
        self._last_sweep_s = time.monotonic()
        self.metrics = DataStreamMetrics(str(server.peer_id))
        # Shard-pinned stream plane (raft.tpu.replication.stream-shards):
        # with loop sharding, each stream's packet handling — channel
        # writes, successor forwards, ack collection — runs on its OWNING
        # DIVISION's loop shard instead of the primary loop (the primary
        # loop's zero-sum cycle share was the attributed cause of
        # mixed-rung stream starvation, docs/perf.md).  streamId -> shard,
        # registered at HEADER routing time on the accept loop.
        self._pin_shards = (server.shards is not None
                            and getattr(server, "stream_shards", True))
        self._stream_shards: Dict[int, int] = {}

    async def start(self) -> None:
        await self.transport.start()

    async def close(self) -> None:
        self.metrics.unregister()
        await self.transport.close()
        for info in list(self._streams.values()):
            await self._cleanup(info)
        for info, _ in list(self._links.values()):
            await self._cleanup(info)
        self._streams.clear()
        self._links.clear()
        self._stream_shards.clear()

    # ------------------------------------------------------------- packets

    async def _expire_idle(self) -> None:
        """Reclaim streams whose client vanished mid-stream and links whose
        raft entry never applied (lazy sweep, cf. MessageStreamRequests)."""
        if self._expiry_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_sweep_s < self._expiry_s / 10:
            return  # keep the per-packet hot path O(1)
        self._last_sweep_s = now
        deadline = now - self._expiry_s
        for sid in [s for s, i in self._streams.items()
                    if i.touched_s < deadline]:
            info = self._streams.pop(sid)
            self._stream_shards.pop(sid, None)
            LOG.warning("expiring abandoned datastream %s", sid)
            await self._cleanup(info)
        for key in [k for k, (_, t) in self._links.items() if t < deadline]:
            info, _ = self._links.pop(key)
            await self._cleanup(info)

    async def _on_packet(self, packet: Packet, conn: PeerConnection) -> None:
        """Accept-loop entry: route the packet to its stream's pinned loop
        shard (the owning division's shard) and run the real handler
        there; unsharded servers — or packets for unknown streams, whose
        handling is just an error reply — stay on the accept loop.  The
        read loop awaits this per packet, so per-stream packet order is
        preserved across the hop."""
        await self._expire_idle()
        if self._pin_shards:
            shard = self._route_shard(packet)
            if shard is not None:
                await self.server.shards.run_on(
                    shard, self._handle_packet(packet, conn))
                return
        await self._handle_packet(packet, conn)

    def _route_shard(self, packet: Packet) -> Optional[int]:
        """Loop shard owning ``packet``'s stream: registered at HEADER
        time from the header's group id (one extra header decode, paid
        once per stream), looked up for DATA/CLOSE.  None = handle on the
        accept loop (undecodable header / unknown stream error paths)."""
        if packet.kind == KIND_HEADER:
            try:
                request, _ = decode_header(packet.data)
            except Exception:
                return None  # the handler produces the failure reply
            shard = self.server.shard_of_group(request.group_id)
            self._stream_shards[packet.stream_id] = shard
            return shard
        return self._stream_shards.get(packet.stream_id)

    async def _handle_packet(self, packet: Packet,
                             conn: PeerConnection) -> None:
        """The real packet handler (on the stream's pinned loop when
        sharded).  HEADER and CLOSE are handled fully inline (once per
        stream).  DATA is PIPELINED: the ordered work — offset check,
        local channel write, putting the forward copies on the successor
        sockets — happens inline (so stream order is the read-loop
        order), but awaiting the successor acks and answering the client
        moves to a completion task, letting the read loop pull the next
        packet immediately.  Serialized per-packet round-trips through the
        whole fan-out chain were the measured throughput ceiling
        (~0.7 MB/s aggregate at 64KB packets); the reference pipelines
        exactly this way by chaining per-stream futures
        (DataStreamManagement.java:85 writeTo/thenCombine chains)."""
        self.metrics.num_requests.inc()
        with self.metrics.request_timer.time():
            reply_data = b""
            try:
                if packet.kind == KIND_HEADER:
                    is_new = packet.stream_id not in self._streams
                    await self._on_header(packet)
                    if is_new:  # count only opens that actually succeeded
                        self.metrics.streams_started.inc()
                elif packet.kind == KIND_DATA:
                    if not packet.is_close:
                        await self._on_data_pipelined(packet, conn)
                        return  # completion task acks the client
                    await self._on_close_data(packet)
                else:
                    raise DataStreamException(f"unexpected kind {packet.kind}")
                if packet.is_close:
                    # inside the try: a failing close must still answer the
                    # client (failure reply) and count as failed
                    reply_data = await self._finish(packet)
                    self.metrics.streams_closed.inc()
            except Exception as e:
                LOG.warning("datastream packet failed: %s", e)
                self.metrics.num_failed.inc()
                await conn.send(Packet(KIND_REPLY, packet.stream_id,
                                       packet.offset,
                                       packet.flags & ~FLAG_SUCCESS, b""))
                return
            await conn.send(Packet(KIND_REPLY, packet.stream_id, packet.offset,
                                   packet.flags | FLAG_SUCCESS, reply_data))

    async def _on_header(self, packet: Packet) -> None:
        request, routing = decode_header(packet.data)
        if packet.stream_id in self._streams:
            return  # idempotent header retry
        is_primary = bool(packet.flags & FLAG_PRIMARY)

        division = self.server.get_division(request.group_id)
        local = await division.state_machine.data_stream(request)

        remotes: list[_RemoteStream] = []
        successors = routing.get_successors(self.server.peer_id)
        if routing.is_empty() and is_primary:
            # documented default: an empty table means the primary fans out
            # to every other peer that serves a datastream address
            successors = tuple(
                p.id for p in division.state.configuration.all_peers()
                if p.id != self.server.peer_id and p.datastream_address)
        for pid in successors:
            peer = division.state.configuration.get_peer(pid)
            if peer is None or not peer.datastream_address:
                raise DataStreamException(
                    f"successor {pid} has no datastream address")
            remotes.append(_RemoteStream(pid, peer.datastream_address,
                             tls=self.tls))

        info = StreamInfo(request, is_primary, local, remotes)
        info.shard = self._stream_shards.get(packet.stream_id)
        self._streams[packet.stream_id] = info
        try:
            forwarded = Packet(KIND_HEADER, packet.stream_id, packet.offset,
                               packet.flags & ~FLAG_PRIMARY, packet.data)
            await asyncio.gather(*(r.connect() for r in remotes))
            await asyncio.gather(*(r.forward(forwarded) for r in remotes))
        except Exception:
            self._streams.pop(packet.stream_id, None)
            await self._cleanup(info)
            raise

    def _info_for(self, packet: Packet) -> StreamInfo:
        info = self._streams.get(packet.stream_id)
        if info is None:
            raise DataStreamException(f"unknown stream {packet.stream_id}")
        return info

    async def _on_data_pipelined(self, packet: Packet,
                                 conn: PeerConnection) -> None:
        """Ordered phase of a (non-close) DATA packet: validate, write the
        local channel, put the forward copies on the wire; then hand the
        ack-collection to a completion task so the read loop pipelines."""
        info = self._info_for(packet)
        info.touched_s = time.monotonic()
        if info.failed is not None:
            raise info.failed
        if packet.offset != info.next_offset:
            raise DataStreamException(
                f"stream {packet.stream_id}: out-of-order offset "
                f"{packet.offset}, expected {info.next_offset}")
        ack_futs: list = []
        try:
            written = await info.local.channel.write(packet.data)
            if written != len(packet.data):
                raise DataStreamException(
                    f"short write {written}/{len(packet.data)}")
            # sends happen NOW, in read-loop order (per-successor FIFO);
            # only the ack futures move to the completion task
            for r in info.remotes:
                ack_futs.append(await r.send(packet))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # Poison the stream OURSELVES (later packets and the CLOSE fail
            # fast server-side instead of relying on the client reacting to
            # the failure reply), and consume/cancel the earlier
            # successors' ack futures — abandoned, their eventual
            # set_exception would surface as 'exception never retrieved'
            # noise with no handler (ADVICE r5).
            info.failed = e if isinstance(e, DataStreamException) \
                else DataStreamException(str(e))
            for fut in ack_futs:
                fut.add_done_callback(_consume_result)
                fut.cancel()
            raise
        info.next_offset += len(packet.data)
        info.bytes_written += len(packet.data)
        if packet.is_sync:
            await info.local.channel.force()

        async def complete() -> None:
            try:
                replies = await asyncio.gather(*ack_futs)
                for r, reply in zip(info.remotes, replies):
                    if not reply.success:
                        raise DataStreamException(
                            f"successor {r.peer_id} rejected stream "
                            f"{packet.stream_id} offset {packet.offset}")
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # poison the stream: later packets and the CLOSE must fail
                info.failed = e
                LOG.warning("datastream packet failed: %s", e)
                self.metrics.num_failed.inc()
                await conn.send(Packet(KIND_REPLY, packet.stream_id,
                                       packet.offset,
                                       packet.flags & ~FLAG_SUCCESS, b""))
                return
            self.metrics.bytes_written.inc(len(packet.data))
            await conn.send(Packet(KIND_REPLY, packet.stream_id,
                                   packet.offset,
                                   packet.flags | FLAG_SUCCESS, b""))

        t = asyncio.create_task(complete())
        info.pending.add(t)
        t.add_done_callback(info.pending.discard)

    async def _on_close_data(self, packet: Packet) -> None:
        """The CLOSE packet's data phase: drain the pipeline first, then the
        fully-awaited ordered path (forwarding the close to successors and
        forcing the local channel)."""
        info = self._info_for(packet)
        info.touched_s = time.monotonic()
        while info.pending:
            await asyncio.gather(*list(info.pending),
                                 return_exceptions=True)
        if info.failed is not None:
            raise info.failed
        if packet.offset != info.next_offset:
            raise DataStreamException(
                f"stream {packet.stream_id}: out-of-order close offset "
                f"{packet.offset}, expected {info.next_offset}")
        if packet.data:
            written = await info.local.channel.write(packet.data)
            if written != len(packet.data):
                raise DataStreamException(
                    f"short write {written}/{len(packet.data)}")
            info.next_offset += len(packet.data)
            info.bytes_written += len(packet.data)
            self.metrics.bytes_written.inc(len(packet.data))
        await asyncio.gather(*(r.forward(packet) for r in info.remotes))
        await info.local.channel.force()

    async def _finish(self, packet: Packet) -> bytes:
        """CLOSE handling after the data landed everywhere: primary submits
        the raft write; reply bytes ride back in the CLOSE ack."""
        info = self._info_for(packet)
        info.closed = True
        self._streams.pop(packet.stream_id, None)
        self._stream_shards.pop(packet.stream_id, None)
        await info.local.channel.close()
        for r in info.remotes:  # successors acked the CLOSE already
            await r.close()
        link_key = (info.request.client_id.to_bytes(), info.request.call_id)
        self._links[link_key] = (info, time.monotonic())
        if not info.is_primary:
            return b""
        reply = await self.server.submit_data_stream_request(info.request)
        if not reply.success:
            self._links.pop(link_key, None)
            await self._cleanup(info)
        return reply.to_bytes()

    async def _cleanup(self, info: StreamInfo) -> None:
        # a shard-pinned stream's tasks and successor connections are
        # loop-affine: unwind them on the loop they live on
        if info.shard is not None and self.server.shards is not None:
            await self.server.shards.run_on(info.shard,
                                            self._cleanup_owned(info))
            return
        await self._cleanup_owned(info)

    async def _cleanup_owned(self, info: StreamInfo) -> None:
        for t in list(info.pending):
            t.cancel()
        info.pending.clear()
        if info.local is not None:
            try:
                await info.local.cleanup()
            except Exception:
                LOG.exception("stream cleanup failed")
        for r in info.remotes:
            await r.close()

    # ----------------------------------------------------- apply-time link

    def take_link(self, client_id: bytes, call_id: int
                  ) -> Optional[StreamInfo]:
        entry = self._links.pop((client_id, call_id), None)
        return entry[0] if entry is not None else None

    @property
    def bound_port(self) -> Optional[int]:
        return self.transport.bound_port
