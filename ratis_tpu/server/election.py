"""Leader election driver (candidate side).

Capability parity with the reference LeaderElection
(ratis-server/.../impl/LeaderElection.java:80): rounds of PRE_VOTE then
ELECTION (runImpl:365-379), parallel vote requests (submitRequests:477),
incremental tallying with priority vetoes and the higher-priority-replied
gate (waitForResults:498-592), early exit on discovered terms, and the
single-mode pass.

The tally math is :mod:`ratis_tpu.ops.reference` — the same algorithm the
batched kernel runs for election storms; one division electing uses the
scalar form directly.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from typing import Optional

from ratis_tpu.ops import reference as ref
from ratis_tpu.protocol.raftrpc import (RaftRpcHeader, RequestVoteReply,
                                        RequestVoteRequest)
from ratis_tpu.protocol.termindex import TermIndex

LOG = logging.getLogger(__name__)


class Phase(enum.Enum):
    PRE_VOTE = "PRE_VOTE"
    ELECTION = "ELECTION"


class Result(enum.Enum):
    PASSED = "PASSED"
    SINGLE_MODE_PASSED = "SINGLE_MODE_PASSED"
    REJECTED = "REJECTED"
    TIMEOUT = "TIMEOUT"
    DISCOVERED_A_NEW_TERM = "DISCOVERED_A_NEW_TERM"
    SHUTDOWN = "SHUTDOWN"
    NOT_IN_CONF = "NOT_IN_CONF"


class LeaderElection:
    def __init__(self, division, force: bool = False):
        self.division = division
        self.force = force  # transfer-leadership skips PRE_VOTE
        # set by change_to_candidate(force=True): the term was already
        # bumped + self-voted synchronously at candidacy start, so the
        # ELECTION phase must not bump again
        self.term_pre_initialized = False
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True
        div = self.division
        if div.engine_slot >= 0:
            # abandon any engine-tallied round immediately (otherwise the
            # awaiting candidate task lingers until the round deadline)
            div.server.engine.end_vote_round(div.engine_slot)

    async def run(self) -> None:
        """One full attempt: optional PRE_VOTE, then ELECTION; on success the
        division becomes leader, otherwise the election deadline re-arms."""
        div = self.division
        conf = div.state.configuration
        if not conf.contains_voting(div.member_id.peer_id):
            LOG.debug("%s not in conf, skip election", div.member_id)
            div.reset_election_deadline()
            return
        div.election_metrics.election_count.inc()
        election_ctx = div.election_metrics.election_timer.time()
        try:
            await self._run_phases()
        finally:
            election_ctx.stop()

    async def _run_phases(self) -> None:
        div = self.division

        if div.pre_vote_enabled and not self.force:
            result, _ = await self._ask_for_votes(Phase.PRE_VOTE)
            if result == Result.DISCOVERED_A_NEW_TERM:
                return  # change_to_follower already happened
            if result not in (Result.PASSED, Result.SINGLE_MODE_PASSED):
                div.reset_election_deadline()
                return
        if self._stopped or not div.is_candidate():
            return

        result, term = await self._ask_for_votes(Phase.ELECTION)
        if self._stopped or not div.is_candidate():
            return
        if result in (Result.PASSED, Result.SINGLE_MODE_PASSED):
            await div.change_to_leader()
        elif result == Result.DISCOVERED_A_NEW_TERM:
            pass  # handled inline
        else:
            await div.change_to_follower(div.state.current_term, None,
                                         reason=f"election {result.value}")

    async def _ask_for_votes(self, phase: Phase) -> tuple[Result, int]:
        div = self.division
        conf = div.state.configuration
        state = div.state

        if phase == Phase.ELECTION:
            term = (state.current_term if self.term_pre_initialized
                    else await state.init_election_term())
        else:
            term = state.current_term + 1  # probe term, nothing persisted

        last = state.log.get_last_entry_term_index() or TermIndex.INITIAL_VALUE
        others = [p for p in conf.voting_peers() if p.id != div.member_id.peer_id]

        if conf.is_single_mode(div.member_id.peer_id) or not others:
            return Result.PASSED, term

        engine = div.server.engine
        if engine.tally_batched and div.engine_slot >= 0:
            return await self._ask_for_votes_batched(phase, term, last,
                                                     others)

        # slot-indexed tallies for ops.reference.tally_votes
        slots = div.peer_slots
        n = div.max_peers
        grants = [False] * n
        rejects = [False] * n
        priority = [0] * n
        conf_cur = [False] * n
        conf_old = [False] * n
        for p in conf.conf.peers:
            s = slots.get(p.id)
            if s is not None:
                conf_cur[s] = True
                priority[s] = p.priority
        if conf.old_conf is not None:
            for p in conf.old_conf.peers:
                s = slots.get(p.id)
                if s is not None:
                    conf_old[s] = True
                    priority[s] = p.priority
        me = div.peer_slots[div.member_id.peer_id]
        grants[me] = True
        self_priority = (conf.get_peer(div.member_id.peer_id).priority
                         if conf.get_peer(div.member_id.peer_id) else 0)

        header = lambda to: RaftRpcHeader(div.member_id.peer_id, to.id,
                                          div.group_id)
        request = lambda to: RequestVoteRequest(
            header(to), term, last, pre_vote=(phase == Phase.PRE_VOTE),
            force=self.force)

        queue: asyncio.Queue = asyncio.Queue()

        async def _one(peer):
            try:
                reply = await div.server.send_server_rpc(peer.id, request(peer))
                await queue.put(reply)
            except Exception as e:
                await queue.put(e)

        tasks = [asyncio.create_task(_one(p)) for p in others]
        deadline = asyncio.get_running_loop().time() + div.random_election_timeout_s()
        outstanding = len(others)
        replied: set = set()
        try:
            while outstanding > 0 and not self._stopped:
                wait = deadline - asyncio.get_running_loop().time()
                if wait <= 0:
                    break
                try:
                    item = await asyncio.wait_for(queue.get(), wait)
                except asyncio.TimeoutError:
                    break
                outstanding -= 1
                if isinstance(item, Exception):
                    continue
                reply: RequestVoteReply = item
                peer_id = reply.header.requestor_id
                if peer_id in replied:
                    continue
                replied.add(peer_id)
                if reply.should_shutdown:
                    return Result.SHUTDOWN, term
                if reply.term > term:
                    await div.change_to_follower(
                        reply.term, None, reason="higher term in vote reply")
                    return Result.DISCOVERED_A_NEW_TERM, reply.term
                s = slots.get(peer_id)
                if s is None:
                    continue
                if reply.granted:
                    grants[s] = True
                else:
                    rejects[s] = True
                passed, _, rejected = ref.tally_votes(
                    grants, rejects, conf_cur, conf_old, priority, self_priority)
                if passed:
                    return Result.PASSED, term
                if rejected:
                    return Result.REJECTED, term
        finally:
            for t in tasks:
                t.cancel()

        # deadline or all replies in: the timeout-path tally
        _, passed_on_timeout, rejected = ref.tally_votes(
            grants, rejects, conf_cur, conf_old, priority, self_priority)
        if passed_on_timeout:
            return Result.PASSED, term
        if conf.is_single_mode(div.member_id.peer_id):
            return Result.SINGLE_MODE_PASSED, term
        return (Result.REJECTED if rejected else Result.TIMEOUT), term

    async def _ask_for_votes_batched(self, phase: Phase, term: int, last,
                                     others) -> tuple[Result, int]:
        """Engine-tallied round (SURVEY §3.3 HOT LOOP #2): vote replies
        stream into the engine as packed events, and ONE jitted
        ops.quorum.tally_votes dispatch per tick decides every concurrent
        round on this server — the scalar per-reply loop above remains the
        differential oracle and the below-threshold path.

        Special replies the tally kernel cannot express (shutdown, a
        higher discovered term) are handled inline by the reply tasks:
        they abandon the engine round and the result is returned directly.
        """
        div = self.division
        engine = div.server.engine
        slot = div.engine_slot
        slots = div.peer_slots
        deadline_ms = (engine.clock.now_ms()
                       + int(div.random_election_timeout_s() * 1000))
        fut = engine.begin_vote_round(slot, deadline_ms)
        special: dict = {}

        header = lambda to: RaftRpcHeader(div.member_id.peer_id, to.id,
                                          div.group_id)
        request = lambda to: RequestVoteRequest(
            header(to), term, last, pre_vote=(phase == Phase.PRE_VOTE),
            force=self.force)

        async def _one(peer):
            try:
                reply = await div.server.send_server_rpc(peer.id,
                                                         request(peer))
            except Exception:
                return
            if fut.done():
                return
            if reply.should_shutdown:
                special["result"] = (Result.SHUTDOWN, term)
                engine.end_vote_round(slot)
                return
            if reply.term > term:
                # record only; the step-down itself runs in the MAIN
                # election coroutine below — doing it here would let the
                # main coroutine's task cleanup cancel change_to_follower
                # mid-transition (role flipped, term never persisted)
                special["result"] = (Result.DISCOVERED_A_NEW_TERM,
                                     reply.term)
                engine.end_vote_round(slot)
                return
            s = slots.get(reply.header.requestor_id)
            if s is not None:
                engine.on_vote_reply(slot, s, reply.granted)

        tasks = [asyncio.create_task(_one(p)) for p in others]

        async def _all_replied():
            # outstanding == 0: resolve now through the timeout-path tally
            # instead of waiting out the randomized round deadline
            await asyncio.gather(*tasks, return_exceptions=True)
            engine.expire_vote_round(slot)

        watcher = asyncio.create_task(_all_replied())
        try:
            result_str = await fut
        except asyncio.CancelledError:
            # Only a deliberate round abandonment (a special reply recorded
            # in ``special`` or stop()) may swallow the cancellation.  The
            # round future being cancelled is NOT proof of that: an external
            # task cancellation can land in the same instant, and proceeding
            # (possibly into change_to_follower) would ignore it.
            if not fut.cancelled() or (not special and not self._stopped):
                raise  # the election task itself was cancelled
            # round abandoned (special reply / stop / step-down)
            result, new_term = special.get("result",
                                           (Result.SHUTDOWN, term))
            if result == Result.DISCOVERED_A_NEW_TERM:
                await div.change_to_follower(
                    new_term, None, reason="higher term in vote reply")
            return result, new_term
        finally:
            watcher.cancel()
            for t in tasks:
                t.cancel()
        if self._stopped:
            return Result.SHUTDOWN, term
        result = {
            "PASSED": Result.PASSED,
            "REJECTED": Result.REJECTED,
            "TIMEOUT": Result.TIMEOUT,
        }[result_str]
        if result in (Result.REJECTED, Result.TIMEOUT) \
                and div.state.configuration.is_single_mode(
                    div.member_id.peer_id):
            # conf shrank to single mode mid-round: the scalar oracle's
            # deadline tally passes here (election.py timeout path)
            return Result.SINGLE_MODE_PASSED, term
        return result, term
