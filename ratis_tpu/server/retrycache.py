"""Retry cache: (clientId, callId) -> reply dedupe for retried writes.

Capability parity with the reference RetryCacheImpl
(ratis-server/.../impl/RetryCacheImpl.java:42): an expiring cache keyed by
(clientId, callId) whose entries hold the reply future; a retried request —
including one retried against a NEW leader after failover — returns the
cached reply instead of re-executing.  Entries are created when a request
enters the write path and completed at apply time, which is what makes the
failover case work: followers populate the cache while applying replicated
entries.  Client-piggybacked replied-call-ids GC entries early (reference
RaftClientImpl.RepliedCallIds).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ratis_tpu.protocol.requests import RaftClientReply

CacheKey = tuple[bytes, int]


class CacheEntry:
    def __init__(self, key: CacheKey):
        self.key = key
        self.future: asyncio.Future = asyncio.get_running_loop().create_future()
        self.created = time.monotonic()

    @property
    def done(self) -> bool:
        return self.future.done()

    def complete(self, reply: RaftClientReply) -> None:
        if not self.future.done():
            self.future.set_result(reply)

    def fail(self) -> None:
        """Invalidate (e.g. leadership lost before apply): the retry must
        re-execute rather than receive a bogus cached failure."""
        if not self.future.done():
            self.future.cancel()


class RetryCache:
    def __init__(self, expiry_s: float = 60.0):
        self._map: dict[CacheKey, CacheEntry] = {}
        self.expiry_s = expiry_s
        self.stats = {"hits": 0, "misses": 0}

    def _expired(self, e: CacheEntry, now: float) -> bool:
        return (now - e.created) > self.expiry_s or e.future.cancelled()

    def get_or_create(self, client_id: bytes, call_id: int
                      ) -> tuple[CacheEntry, bool]:
        """Returns (entry, is_new)."""
        key = (client_id, call_id)
        now = time.monotonic()
        e = self._map.get(key)
        if e is not None and not self._expired(e, now):
            self.stats["hits"] += 1
            return e, False
        self.stats["misses"] += 1
        e = CacheEntry(key)
        self._map[key] = e
        return e, True

    def get(self, client_id: bytes, call_id: int) -> Optional[CacheEntry]:
        e = self._map.get((client_id, call_id))
        if e is not None and self._expired(e, time.monotonic()):
            return None
        return e

    def get_or_create_on_apply(self, client_id: bytes, call_id: int) -> CacheEntry:
        """Apply path (any role): ensure an entry exists so post-failover
        retries hit the cache on the new leader."""
        e, _ = self.get_or_create(client_id, call_id)
        return e

    def evict_replied(self, client_id: bytes, call_ids) -> None:
        for cid in call_ids:
            self._map.pop((client_id, cid), None)

    def sweep(self) -> int:
        """Drop expired entries; called opportunistically by the apply loop
        (or, in upkeep-plane mode, when the expiry waterline fires)."""
        now = time.monotonic()
        dead = [k for k, e in self._map.items() if self._expired(e, now)]
        for k in dead:
            del self._map[k]
        return len(dead)

    def next_expiry_s(self) -> float:
        """Oldest entry's expiry time — the upkeep plane's CH_CACHE
        waterline.  +inf when empty, so an idle division arms nothing.
        O(n), but only paid when the waterline actually fires (at most
        once per expiry window per division holding entries), never on
        the per-sweep tick."""
        if not self._map:
            return float("inf")
        return min(e.created for e in self._map.values()) + self.expiry_s

    def __len__(self) -> int:
        return len(self._map)
