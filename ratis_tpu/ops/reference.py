"""Scalar (per-group, pure-Python) reference implementation of the quorum math.

This is the readable specification of :mod:`ratis_tpu.ops.quorum` — a direct
transliteration of the reference algorithms (LeaderStateImpl.getMajorityMin /
MinMajorityMax.getMajority LeaderStateImpl.java:865-933,
LeaderElection.waitForResults LeaderElection.java:498-592,
RaftConfigurationImpl.hasMajority:265-281) operating on one group at a time.
Used (a) as the differential-test oracle for the batched kernels and (b) as
the small-G fast path where a device dispatch isn't worth the latency.
"""

from __future__ import annotations

from typing import Optional, Sequence

# Sentinel for empty confs; matches the batched kernels' dtype-min for the
# engine's default int32 index arrays.  Callers using another dtype must pass
# a matching ``empty`` so the scalar fast path and the kernel agree exactly.
INT_MIN = -(2 ** 31)


def majority_count(size: int) -> int:
    return size // 2 + 1


def majority_min(values: Sequence[int], mask: Sequence[bool],
                 empty: int = INT_MIN) -> int:
    """Greatest v such that a majority of members have value >= v."""
    members = sorted(v for v, m in zip(values, mask) if m)
    if not members:
        return empty
    return members[(len(members) - 1) // 2]


def combined_majority_min(values: Sequence[int], conf_cur: Sequence[bool],
                          conf_old: Sequence[bool]) -> int:
    maj = majority_min(values, conf_cur)
    if any(conf_old):
        maj = min(maj, majority_min(values, conf_old))
    return maj


def update_commit(match_index: Sequence[int], self_slot: int, flush_index: int,
                  conf_cur: Sequence[bool], conf_old: Sequence[bool],
                  commit_index: int, first_leader_index: int,
                  is_leader: bool) -> tuple[int, bool]:
    eff = [flush_index if i == self_slot else v for i, v in enumerate(match_index)]
    candidate = combined_majority_min(eff, conf_cur, conf_old)
    if is_leader and candidate > commit_index and candidate >= first_leader_index:
        return candidate, True
    return commit_index, False


def all_replicated_min(match_index: Sequence[int], self_slot: int,
                       flush_index: int, conf_cur: Sequence[bool],
                       conf_old: Sequence[bool], empty: int = INT_MIN) -> int:
    eff = [flush_index if i == self_slot else v for i, v in enumerate(match_index)]
    union = [c or o for c, o in zip(conf_cur, conf_old)]
    members = [v for v, m in zip(eff, union) if m]
    return min(members) if members else empty


def has_majority(grants: Sequence[bool], mask: Sequence[bool]) -> bool:
    size = sum(mask)
    cnt = sum(1 for g, m in zip(grants, mask) if g and m)
    return cnt >= majority_count(size)


def majority_rejected(rejects: Sequence[bool], mask: Sequence[bool]) -> bool:
    size = sum(mask)
    if size == 0:
        return False
    cnt = sum(1 for r, m in zip(rejects, mask) if r and m)
    return cnt >= (size + 1) // 2


def tally_votes(grants: Sequence[bool], rejects: Sequence[bool],
                conf_cur: Sequence[bool], conf_old: Sequence[bool],
                priority: Sequence[int], self_priority: int
                ) -> tuple[bool, bool, bool]:
    """Returns (passed, passed_on_timeout, rejected); see quorum.tally_votes."""
    in_joint = any(conf_old)
    majority = has_majority(grants, conf_cur) and (
        not in_joint or has_majority(grants, conf_old))

    union = [c or o for c, o in zip(conf_cur, conf_old)]
    higher = [u and p > self_priority for u, p in zip(union, priority)]
    veto = any(r and h for r, h in zip(rejects, higher))
    rej = majority_rejected(rejects, conf_cur) or (
        in_joint and majority_rejected(rejects, conf_old))
    rejected = veto or rej

    hp_all_replied = all((g or r) for g, r, h in zip(grants, rejects, higher) if h) \
        if any(higher) else True
    passed = majority and hp_all_replied and not rejected
    passed_on_timeout = majority and not rejected
    return passed, passed_on_timeout, rejected


def check_leadership(last_ack_ms: Sequence[int], self_slot: int,
                     conf_cur: Sequence[bool], conf_old: Sequence[bool],
                     now_ms: int, timeout_ms: int, is_leader: bool) -> bool:
    if not is_leader:
        return False
    eff = [now_ms if i == self_slot else v for i, v in enumerate(last_ack_ms)]
    quorum_ack = combined_majority_min(eff, conf_cur, conf_old)
    return (now_ms - quorum_ack) > timeout_ms


def lease_expiry(last_ack_ms: Sequence[int], self_slot: int,
                 conf_cur: Sequence[bool], conf_old: Sequence[bool],
                 lease_timeout_ms: int, big: int = 2 ** 31 - 1) -> int:
    """``big`` must be the dtype max of the engine's time arrays (int32 by
    default) so this scalar path and the batched kernel agree exactly."""
    eff = [big if i == self_slot else v for i, v in enumerate(last_ack_ms)]
    quorum_ack = combined_majority_min(eff, conf_cur, conf_old)
    return min(quorum_ack, big - lease_timeout_ms) + lease_timeout_ms
