"""Fused lag & health ledger pass over the ``[G, P]`` group batch.

One XLA dispatch per telemetry tick turns the consensus state the
QuorumEngine already owns (match/commit/applied indexes, conf masks, ack
times) into every per-group and per-peer observability quantity the host
consumers need — per-follower lag, commit−applied gaps, device-side log2
lag histograms (scatter-add bincount, no host loop), per-group commit
deltas for the hot-group sketch, and the per-peer link counts behind the
grey-follower health score — packed into ONE int32 vector so the sample
costs exactly one device→host transfer.  This replaces the G-length
Python division walks the telemetry sampler (metrics/timeseries.py) and
the stall watchdog (server/watchdog.py) ran per pass; the reference
exposes the same signals only as per-group scalars through
RaftServerMetrics on the Metrics SPI.

Conventions match ops.quorum: indices and millisecond times are int32,
``[G, P]`` membership masks are bool, every function is total (callers
mask; unused lanes compute garbage that the masks zero out), and the
peer axis carries a ``peer_index`` column map into the server-wide dense
peer table (-1 = unmapped column).
"""

from __future__ import annotations

import jax.numpy as jnp

from ratis_tpu.engine.roles import ROLE_LEADER, ROLE_UNUSED

# log2 lag histogram width: bucket 0 = caught up (lag 0), bucket i >= 1 =
# lag in [2^(i-1), 2^i) entries.  31 thresholds covers any int32 lag.
LAG_BUCKETS = 32

# packed-output section names, in order, with per-section width factors
# expressed over (g, num_peers); see pack_slices()
_SECTIONS = (("gap", "g"), ("delta", "g"), ("worst_lag", "g"),
             ("worst_peer", "g"), ("hist", "hist"), ("peer_links", "p"),
             ("peer_up", "p"), ("peer_laggy", "p"), ("peer_active", "p"),
             ("peer_laggy_active", "p"), ("peer_max_lag", "p"),
             ("scalars", "s"))


def pack_slices(g: int, num_peers: int) -> dict:
    """Slice of each section inside the packed int32 output vector."""
    widths = {"g": g, "hist": num_peers * LAG_BUCKETS, "p": num_peers,
              "s": 2}
    out, off = {}, 0
    for name, kind in _SECTIONS:
        w = widths[kind]
        out[name] = slice(off, off + w)
        off += w
    return out


def packed_size(g: int, num_peers: int) -> int:
    return 4 * g + num_peers * (LAG_BUCKETS + 6) + 2


def lag_buckets(lag: jnp.ndarray) -> jnp.ndarray:
    """log2 bucket of a non-negative lag: exact integer compare-sum
    (bit_length), never a float log whose rounding would misfile the
    power-of-two boundaries."""
    thresholds = jnp.left_shift(
        jnp.int32(1), jnp.arange(LAG_BUCKETS - 1, dtype=jnp.int32))
    return jnp.sum(lag[..., None] >= thresholds, axis=-1,
                   dtype=jnp.int32)


def ledger_pass(role, match_index, commit_index, applied_index,
                conf_cur, conf_old, self_mask, last_ack_ms, peer_index,
                prev_commit, prev_valid, now_ms, lag_threshold,
                up_window_ms, *, num_peers: int) -> jnp.ndarray:
    """The fused observability pass.  All array args keep the engine's
    host-mirror dtypes; ``num_peers`` is static (the dense peer-table
    width, rounded up so table growth rarely recompiles).  Returns the
    packed int32 vector described by :func:`pack_slices`:

    - ``gap [G]``: commit − applied per active group (apply backlog).
    - ``delta [G]``: commit advance since the caller's previous pass,
      leader rows with a valid baseline only (hot-group sketch feed).
    - ``worst_lag [G]`` / ``worst_peer [G]``: the laggiest follower link
      per leader row (entries behind commit / dense peer id), -1 where
      the row has no follower links (non-leader or unused).
    - ``hist [num_peers * LAG_BUCKETS]``: per-peer log2 lag histogram
      over every follower link, scatter-add on device.
    - ``peer_* [num_peers]``: link counts per peer across all groups the
      local server leads — total, up (acked within ``up_window_ms``),
      laggy (>= ``lag_threshold`` entries behind), active (up links of
      groups that advanced this pass), laggy_active, and max lag — the
      numerators of the grey-follower health score.
    - ``scalars [2]``: leader-row count, summed commit−applied gap.
    """
    active = role != ROLE_UNUSED
    is_leader = role == ROLE_LEADER
    member = (conf_cur | conf_old) & (~self_mask)
    valid = member & is_leader[:, None] & (peer_index >= 0)
    lag = jnp.where(valid,
                    jnp.maximum(commit_index[:, None] - match_index, 0), 0)
    lag_or_none = jnp.where(valid, lag, -1)
    worst_col = jnp.argmax(lag_or_none, axis=1)
    worst_lag = jnp.take_along_axis(lag_or_none, worst_col[:, None],
                                    axis=1)[:, 0]
    worst_peer = jnp.where(
        worst_lag >= 0,
        jnp.take_along_axis(peer_index, worst_col[:, None], axis=1)[:, 0],
        -1)
    gap = jnp.where(active,
                    jnp.maximum(commit_index - applied_index, 0), 0)
    delta = jnp.where(is_leader & prev_valid,
                      jnp.maximum(commit_index - prev_commit, 0), 0)
    # Per-peer aggregation is scatter-FREE: with a num_peers-wide dense
    # table, a [G, P, num_peers] membership one-hot reduced over (G, P)
    # beats jnp scatter by ~4x on XLA CPU (each scatter op carries
    # ~0.5ms of fixed serial overhead; seven of them dominated the whole
    # pass).  Invalid lanes carry peer_index -1, which matches no table
    # column — the same drop semantics the scatter had.
    bucket = lag_buckets(lag)
    onehot = valid[..., None] & (
        peer_index[..., None] == jnp.arange(num_peers, dtype=jnp.int32))
    # histogram as an einsum of the peer one-hot against the bucket
    # one-hot: [G*P, num_peers] x [G*P, LAG_BUCKETS] -> counts.  f32
    # accumulation is exact here (counts are bounded by G*P << 2^24).
    hist = jnp.einsum(
        "np,nb->pb",
        onehot.reshape(-1, num_peers).astype(jnp.float32),
        (bucket[..., None] == jnp.arange(LAG_BUCKETS, dtype=jnp.int32)
         ).reshape(-1, LAG_BUCKETS).astype(jnp.float32),
    ).astype(jnp.int32).ravel()
    up = valid & ((now_ms - last_ack_ms) <= up_window_ms)
    laggy = valid & (lag >= lag_threshold)
    link_active = up & (delta > 0)[:, None]
    laggy_active = link_active & laggy

    def _per_peer(mask):
        return jnp.sum(onehot & mask[..., None], axis=(0, 1),
                       dtype=jnp.int32)

    peer_max_lag = jnp.max(jnp.where(onehot, lag[..., None], -1),
                           axis=(0, 1))
    scalars = jnp.stack([jnp.sum(is_leader, dtype=jnp.int32),
                         jnp.sum(gap, dtype=jnp.int32)])
    return jnp.concatenate([
        gap, delta, worst_lag, worst_peer, hist, _per_peer(valid),
        _per_peer(up), _per_peer(laggy), _per_peer(link_active),
        _per_peer(laggy_active), peer_max_lag, scalars])
