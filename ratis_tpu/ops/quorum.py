"""Batched quorum kernels: the consensus math of every group in one dispatch.

This module is the point of the framework.  The reference runs, per RaftGroup,
a Java event loop that (a) advances the leader commit index by sorting
follower matchIndexes (LeaderStateImpl.updateCommit/getMajorityMin,
ratis-server/.../impl/LeaderStateImpl.java:907,917 and
MinMajorityMax.getMajority:898), (b) tallies election votes with priority
vetoes (LeaderElection.waitForResults, .../impl/LeaderElection.java:498-592),
(c) detects election timeouts (FollowerState.java:64) and leader-lease /
leadership staleness (LeaderLease.java:90, LeaderStateImpl.checkLeadership:1096).
Here all four are pure, shape-stable jnp functions over ``[G, P]`` arrays
(G = group slots, P = peer slots) that XLA compiles into a single program —
one dispatch advances every group a host serves.

Conventions:
- Peer sets are boolean masks over the fixed P axis.  Joint consensus
  (reference RaftConfigurationImpl.hasMajority:265-281) is two masks:
  ``conf_cur`` and ``conf_old`` (all-False when not in joint mode).
  Listeners are simply never in a mask.
- Indices are integer arrays (int32 by default, dtype-polymorphic).
- Times are int32 milliseconds since the engine's *epoch*.  int32 would wrap
  after ~24.8 days, so the engine periodically REBASES the epoch (shifts its
  clock origin and subtracts the same delta from every stored time array,
  QuorumEngine._maybe_rebase_epoch) — comparisons here are all relative, so
  a uniform shift is invisible to the kernels.  int64 on device would require
  jax x64 mode (which silently downcasts otherwise) and is emulated on TPU.
- All functions are total: group slots that are unused/not-leader must be
  masked by the caller (the engine passes role masks).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ratis_tpu.engine.roles import (ROLE_CANDIDATE, ROLE_FOLLOWER,  # noqa: F401
                                    ROLE_LEADER, ROLE_LISTENER, ROLE_UNUSED)


def conf_size(mask: jax.Array) -> jax.Array:
    """[G, P] bool -> [G] number of voting members."""
    return jnp.sum(mask, axis=-1)


def majority_count(mask: jax.Array) -> jax.Array:
    """[G, P] bool -> [G] votes needed for majority: floor(size/2) + 1."""
    return conf_size(mask) // 2 + 1


def majority_min(values: jax.Array, mask: jax.Array) -> jax.Array:
    """Per group, the greatest v such that a majority of members have
    value >= v — i.e. ascending-sorted member values at position (k-1)//2
    (exactly MinMajorityMax.getMajority, LeaderStateImpl.java:898).

    values: [G, P] int; mask: [G, P] bool.  Groups with an empty mask get
    dtype-min (never advances anything).
    """
    big = jnp.array(jnp.iinfo(values.dtype).max, values.dtype)
    masked = jnp.where(mask, values, big)  # non-members sort to the top
    sorted_asc = jnp.sort(masked, axis=-1)
    k = conf_size(mask)
    pos = jnp.maximum(k - 1, 0) // 2
    maj = jnp.take_along_axis(sorted_asc, pos[:, None], axis=-1)[:, 0]
    small = jnp.array(jnp.iinfo(values.dtype).min, values.dtype)
    return jnp.where(k > 0, maj, small)


def combined_majority_min(values: jax.Array, conf_cur: jax.Array,
                          conf_old: jax.Array) -> jax.Array:
    """Joint-consensus combine: min over both confs when conf_old is active
    (reference LeaderStateImpl.java:876 'combine' of MinMajorityMax)."""
    maj_cur = majority_min(values, conf_cur)
    in_joint = jnp.any(conf_old, axis=-1)
    maj_old = majority_min(values, conf_old)
    return jnp.where(in_joint, jnp.minimum(maj_cur, maj_old), maj_cur)


class CommitUpdate(NamedTuple):
    new_commit: jax.Array     # [G] advanced commit index
    changed: jax.Array        # [G] bool: commit advanced this step


def update_commit(match_index: jax.Array, self_mask: jax.Array,
                  flush_index: jax.Array, conf_cur: jax.Array,
                  conf_old: jax.Array, commit_index: jax.Array,
                  first_leader_index: jax.Array,
                  is_leader: jax.Array) -> CommitUpdate:
    """Advance every group's commit index from follower matchIndexes.

    Mirrors LeaderStateImpl.updateCommit:907 -> getMajorityMin:917:
    the leader's own slot contributes its log *flush* index; the majority-min
    over (current ∧ old) confs becomes the candidate commit; it only takes
    effect if it reaches an entry of the current leader term — here encoded as
    ``candidate >= first_leader_index`` (every index >= the leader's startup
    placeholder entry has the leader's term, cf. StartupLogEntry:293), which
    is the Raft §5.4.2 leader-completeness gate.

    match_index: [G, P]; self_mask: [G, P] one-hot of the leader slot;
    flush_index, commit_index, first_leader_index: [G]; is_leader: [G] bool.
    """
    eff = jnp.where(self_mask, flush_index[:, None], match_index)
    candidate = combined_majority_min(eff, conf_cur, conf_old)
    ok = is_leader & (candidate > commit_index) & (candidate >= first_leader_index)
    new_commit = jnp.where(ok, candidate, commit_index)
    return CommitUpdate(new_commit, ok)


def all_replicated_min(match_index: jax.Array, self_mask: jax.Array,
                       flush_index: jax.Array, conf_cur: jax.Array,
                       conf_old: jax.Array) -> jax.Array:
    """Per group, min index replicated on ALL members (for watch ALL /
    ALL_COMMITTED levels, reference WatchRequests + LeaderStateImpl:986)."""
    eff = jnp.where(self_mask, flush_index[:, None], match_index)
    union = conf_cur | conf_old
    big = jnp.array(jnp.iinfo(eff.dtype).max, eff.dtype)
    vals = jnp.where(union, eff, big)
    m = jnp.min(vals, axis=-1)
    small = jnp.array(jnp.iinfo(eff.dtype).min, eff.dtype)
    return jnp.where(jnp.any(union, axis=-1), m, small)


class VoteTally(NamedTuple):
    passed: jax.Array             # [G] bool: strict mid-stream PASS
    passed_on_timeout: jax.Array  # [G] bool: PASS if the round deadline fires now
    rejected: jax.Array           # [G] bool: reject majority or priority veto
    decided: jax.Array            # [G] bool: passed | rejected


def _has_majority(grants: jax.Array, mask: jax.Array) -> jax.Array:
    cnt = jnp.sum(grants & mask, axis=-1)
    return cnt >= majority_count(mask)


def _majority_rejected(rejects: jax.Array, mask: jax.Array) -> jax.Array:
    # Grant majority becomes impossible once ceil(size/2) members rejected
    # (reference PeerConfiguration.majorityRejectVotes, PeerConfiguration.java:175).
    cnt = jnp.sum(rejects & mask, axis=-1)
    k = conf_size(mask)
    return (k > 0) & (cnt >= (k + 1) // 2)


def tally_votes(grants: jax.Array, rejects: jax.Array, conf_cur: jax.Array,
                conf_old: jax.Array, priority: jax.Array,
                self_priority: jax.Array) -> VoteTally:
    """Tally one election round for every group.

    Mirrors LeaderElection.waitForResults (LeaderElection.java:498-592):
    - REJECTED: any *rejecting* member with priority > candidate priority
      (the unconditional veto, LeaderElection.java:554-556), or a reject
      majority in either active conf (majorityRejectVotes,
      PeerConfiguration.java:175).
    - ``passed`` (strict / mid-stream): grant majority in current conf AND
      (if joint) old conf, AND every higher-priority member has replied
      (``higherPriorityPeers.isEmpty()`` gate, LeaderElection.java:569-572),
      and not rejected.
    - ``passed_on_timeout``: majority and not rejected — the round-deadline
      path where unresponsive higher-priority peers no longer block
      (LeaderElection.java:515-519).  The engine picks this when the
      election deadline fires.
    The candidate's own grant must be pre-set in ``grants`` by the caller.
    grants/rejects: [G, P] bool; priority: [G, P] int; self_priority: [G] int.
    """
    in_joint = jnp.any(conf_old, axis=-1)
    pass_cur = _has_majority(grants, conf_cur)
    pass_old = jnp.where(in_joint, _has_majority(grants, conf_old), True)
    majority = pass_cur & pass_old

    union = conf_cur | conf_old
    higher = union & (priority > self_priority[:, None])
    veto = jnp.any(rejects & higher, axis=-1)
    rej_any = _majority_rejected(rejects, conf_cur) | (
        in_joint & _majority_rejected(rejects, conf_old))
    rejected = veto | rej_any

    replied = grants | rejects
    hp_all_replied = jnp.all(~higher | replied, axis=-1)
    passed = majority & hp_all_replied & ~rejected
    passed_on_timeout = majority & ~rejected
    return VoteTally(passed, passed_on_timeout, rejected, passed | rejected)


def election_timeout(now_ms: jax.Array, next_deadline_ms: jax.Array,
                     is_follower: jax.Array) -> jax.Array:
    """[G] bool: followers whose randomized election deadline has passed
    (FollowerState.run's timeout check, FollowerState.java:64+)."""
    return is_follower & (now_ms >= next_deadline_ms)


def check_leadership(last_ack_ms: jax.Array, self_mask: jax.Array,
                     conf_cur: jax.Array, conf_old: jax.Array,
                     now_ms: jax.Array, timeout_ms: jax.Array,
                     is_leader: jax.Array) -> jax.Array:
    """[G] bool step-down mask: leaders that have NOT heard from a quorum
    within the election timeout (LeaderStateImpl.checkLeadership:1096).

    last_ack_ms: [G, P] last AppendEntries-reply time per peer; the leader's
    own slot always counts as fresh.
    """
    eff = jnp.where(self_mask, now_ms, last_ack_ms)
    # Majority-min of ack times = newest time a quorum acked at or after.
    quorum_ack = combined_majority_min(eff, conf_cur, conf_old)
    stale = (now_ms - quorum_ack) > timeout_ms
    return is_leader & stale


def lease_expiry(last_ack_ms: jax.Array, self_mask: jax.Array,
                 conf_cur: jax.Array, conf_old: jax.Array,
                 lease_timeout_ms: jax.Array) -> jax.Array:
    """[G] lease expiry time: majority-ack timestamp + lease timeout
    (reference LeaderLease.getMaxTimestampWithMajorityAck:90).  A leader may
    serve reads locally while now < expiry."""
    big = jnp.array(jnp.iinfo(last_ack_ms.dtype).max, last_ack_ms.dtype)
    eff = jnp.where(self_mask, big, last_ack_ms)
    quorum_ack = combined_majority_min(eff, conf_cur, conf_old)
    # Saturating add: a single-member conf yields quorum_ack == dtype-max
    # (lease forever); adding the timeout must not wrap negative.
    return jnp.minimum(quorum_ack, big - lease_timeout_ms) + lease_timeout_ms


def apply_ack_events(match_index: jax.Array, last_ack_ms: jax.Array,
                     ev_group: jax.Array, ev_peer: jax.Array,
                     ev_match: jax.Array, ev_time_ms: jax.Array,
                     ev_valid: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Scatter a packed batch of AppendEntries acks into the state arrays.

    This replaces the reference's per-stream AppendLogResponseHandler ->
    FollowerInfo.updateMatchIndex -> EventQueue hop (GrpcLogAppender.java:475,
    LeaderStateImpl.onFollowerSuccessAppendEntries:808): the transport layer
    appends (group, peer, matchIndex, time) tuples to a ring buffer and the
    engine flushes them here in one scatter-max.

    ev_*: [E] padded event arrays; invalid slots must have ev_valid False.
    matchIndex is monotone (scatter-max); ack time takes the max too.
    """
    small_i = jnp.array(jnp.iinfo(match_index.dtype).min, match_index.dtype)
    small_t = jnp.array(jnp.iinfo(last_ack_ms.dtype).min, last_ack_ms.dtype)
    m = jnp.where(ev_valid, ev_match, small_i)
    t = jnp.where(ev_valid, ev_time_ms, small_t)
    g = jnp.where(ev_valid, ev_group, 0)
    p = jnp.where(ev_valid, ev_peer, 0)
    new_match = match_index.at[g, p].max(m, mode="drop")
    new_ack = last_ack_ms.at[g, p].max(t, mode="drop")
    return new_match, new_ack


class EngineStep(NamedTuple):
    match_index: jax.Array    # [G, P] updated
    last_ack_ms: jax.Array    # [G, P] updated
    new_commit: jax.Array     # [G]
    commit_changed: jax.Array # [G] bool
    timeouts: jax.Array       # [G] bool followers to become candidates
    stale: jax.Array          # [G] bool leaders that lost quorum contact


def engine_step(match_index: jax.Array, last_ack_ms: jax.Array,
                ev_group: jax.Array, ev_peer: jax.Array, ev_match: jax.Array,
                ev_time_ms: jax.Array, ev_valid: jax.Array,
                self_mask: jax.Array, flush_index: jax.Array,
                conf_cur: jax.Array, conf_old: jax.Array,
                commit_index: jax.Array, first_leader_index: jax.Array,
                role: jax.Array, election_deadline_ms: jax.Array,
                now_ms: jax.Array, leadership_timeout_ms: jax.Array
                ) -> EngineStep:
    """One fused engine tick for every group a host serves: scatter the packed
    ack batch, advance commits, fire election timeouts, detect stale leaders.

    This is the framework's flagship compiled program — the single XLA
    dispatch that replaces the reference's per-division EventProcessor +
    FollowerState + checkLeadership daemons (LeaderStateImpl.java:108-190,
    FollowerState.java:64, LeaderStateImpl.java:1096) for the whole server.
    Role codes match engine.state: 1=follower, 3=leader.
    """
    match_index, last_ack_ms = apply_ack_events(
        match_index, last_ack_ms, ev_group, ev_peer, ev_match, ev_time_ms,
        ev_valid)
    is_leader = role == ROLE_LEADER
    cu = update_commit(match_index, self_mask, flush_index, conf_cur,
                       conf_old, commit_index, first_leader_index, is_leader)
    timeouts = election_timeout(now_ms, election_deadline_ms,
                                role == ROLE_FOLLOWER)
    stale = check_leadership(last_ack_ms, self_mask, conf_cur, conf_old,
                             now_ms, leadership_timeout_ms, is_leader)
    return EngineStep(match_index, last_ack_ms, cu.new_commit, cu.changed,
                      timeouts, stale)


class DeviceState(NamedTuple):
    """The consensus state arrays that live on device between ticks.

    Field order matters: engine_step_resident donates these buffers and
    returns the updated tuple, so the whole [G, P] batch never round-trips
    the host (VERDICT r1 item 4 / SURVEY §7 hard-part 1).  The host keeps a
    numpy mirror it mutates freely; per tick it uploads only the rows whose
    slots changed (``rf_*``) plus the packed ack events (``ev_*``).
    """

    match_index: jax.Array          # [G, P] int32
    last_ack_ms: jax.Array          # [G, P] int32
    self_mask: jax.Array            # [G, P] bool
    conf_cur: jax.Array             # [G, P] bool
    conf_old: jax.Array             # [G, P] bool
    role: jax.Array                 # [G] int8
    flush_index: jax.Array          # [G] int32
    commit_index: jax.Array         # [G] int32
    first_leader_index: jax.Array   # [G] int32
    election_deadline_ms: jax.Array # [G] int32


class ResidentStep(NamedTuple):
    state: DeviceState
    new_commit: jax.Array      # [G]
    commit_changed: jax.Array  # [G] bool
    timeouts: jax.Array        # [G] bool
    stale: jax.Array           # [G] bool


def _scatter_rows(dst: jax.Array, idx: jax.Array, rows: jax.Array) -> jax.Array:
    """Overwrite dst[idx] with rows; idx entries >= len(dst) are dropped
    (invalid refresh slots are padded with an out-of-range index)."""
    return dst.at[idx].set(rows, mode="drop")


def engine_step_resident(state: DeviceState,
                         rf_idx: jax.Array, rf_match: jax.Array,
                         rf_ack: jax.Array, rf_self_mask: jax.Array,
                         rf_conf_cur: jax.Array, rf_conf_old: jax.Array,
                         rf_role: jax.Array, rf_flush: jax.Array,
                         rf_commit: jax.Array, rf_first_leader: jax.Array,
                         rf_deadline: jax.Array,
                         ev_group: jax.Array, ev_peer: jax.Array,
                         ev_match: jax.Array, ev_time_ms: jax.Array,
                         ev_valid: jax.Array,
                         now_ms: jax.Array, leadership_timeout_ms: jax.Array
                         ) -> ResidentStep:
    """Device-resident engine tick: refresh dirty rows, scatter acks, advance.

    Refresh is applied BEFORE the ack scatter so an ack event packed in the
    same tick as a row refresh (e.g. a leader reset) still lands on top of
    the refreshed row — matching the host mirror, which applies events last.
    The kernel writes its own outputs back into the returned state (commit
    indexes advance, fired election deadlines disarm), so host and device
    stay in agreement without a download of the full batch: the host applies
    the same updates from the [G] outputs.
    """
    st = state._replace(
        match_index=_scatter_rows(state.match_index, rf_idx, rf_match),
        last_ack_ms=_scatter_rows(state.last_ack_ms, rf_idx, rf_ack),
        self_mask=_scatter_rows(state.self_mask, rf_idx, rf_self_mask),
        conf_cur=_scatter_rows(state.conf_cur, rf_idx, rf_conf_cur),
        conf_old=_scatter_rows(state.conf_old, rf_idx, rf_conf_old),
        role=_scatter_rows(state.role, rf_idx, rf_role),
        flush_index=_scatter_rows(state.flush_index, rf_idx, rf_flush),
        commit_index=_scatter_rows(state.commit_index, rf_idx, rf_commit),
        first_leader_index=_scatter_rows(state.first_leader_index, rf_idx,
                                         rf_first_leader),
        election_deadline_ms=_scatter_rows(state.election_deadline_ms, rf_idx,
                                           rf_deadline))
    match_index, last_ack_ms = apply_ack_events(
        st.match_index, st.last_ack_ms, ev_group, ev_peer, ev_match,
        ev_time_ms, ev_valid)
    is_leader = st.role == ROLE_LEADER
    cu = update_commit(match_index, st.self_mask, st.flush_index, st.conf_cur,
                       st.conf_old, st.commit_index, st.first_leader_index,
                       is_leader)
    timeouts = election_timeout(now_ms, st.election_deadline_ms,
                                st.role == ROLE_FOLLOWER)
    stale = check_leadership(last_ack_ms, st.self_mask, st.conf_cur,
                             st.conf_old, now_ms, leadership_timeout_ms,
                             is_leader)
    no_deadline = jnp.array(jnp.iinfo(st.election_deadline_ms.dtype).max,
                            st.election_deadline_ms.dtype)
    out_state = st._replace(
        match_index=match_index,
        last_ack_ms=last_ack_ms,
        commit_index=cu.new_commit,
        election_deadline_ms=jnp.where(timeouts, no_deadline,
                                       st.election_deadline_ms))
    return ResidentStep(out_state, cu.new_commit, cu.changed, timeouts, stale)


class ResidentFastStep(NamedTuple):
    state: DeviceState
    # int32 [4, G]: new_commit; commit_changed/timeouts/stale as 0/1 —
    # packed so the host downloads ONE array per tick instead of four
    out: jax.Array


# "no value" sentinel for packed update columns
PACK_SENTINEL = -(2 ** 31)


def engine_step_resident_fast(state: DeviceState, ev_packed: jax.Array,
                              meta: jax.Array) -> ResidentFastStep:
    """The steady-state tick: the per-tick transfer surface is exactly TWO
    uploads + ONE download.

    ``ev_packed`` is int32 [7, E]; each column is either an ack event or a
    slot update (flush advance / election-deadline re-arm — the high-rate
    host mutations that would otherwise force a dirty-row refresh on every
    tick):

      row 0: group slot
      row 1: peer slot            (ack columns; 0 otherwise)
      row 2: match index          (ack columns; PACK_SENTINEL otherwise)
      row 3: ack time ms          (ack columns; PACK_SENTINEL otherwise)
      row 4: ack valid 0/1
      row 5: new flush index      (update columns; PACK_SENTINEL otherwise)
      row 6: new election deadline(update columns; PACK_SENTINEL otherwise)

    ``meta`` is int32 [2]: (now_ms, leadership_timeout_ms).  ``out`` is
    int32 [4, G]: (new_commit, commit_changed, timeouts, stale).

    Profiling the e2e benchmark showed the unpacked resident step spending
    more time in 18 small host->device transfers per tick than in the math;
    packing collapses that to the minimum XLA dispatch overhead.  Rare
    mutations (role/conf changes, match regressions) still go through the
    dirty-row refresh in engine_step_resident.
    """
    slot = ev_packed[0]
    ev_peer = ev_packed[1]
    ev_match, ev_time_ms = ev_packed[2], ev_packed[3]
    ev_valid = ev_packed[4] != 0
    up_flush, up_deadline = ev_packed[5], ev_packed[6]
    now_ms = meta[0]
    leadership_timeout_ms = meta[1]
    cap = state.flush_index.shape[0]
    sent = jnp.int32(PACK_SENTINEL)

    # slot updates first: a deadline re-armed in the same tick must be seen
    # by the timeout check below (matches the host mirror, updated at call)
    fidx = jnp.where(up_flush != sent, slot, cap)
    flush_index = state.flush_index.at[fidx].max(up_flush, mode="drop")
    didx = jnp.where(up_deadline != sent, slot, cap)
    election_deadline_ms = state.election_deadline_ms.at[didx].set(
        up_deadline, mode="drop")

    match_index, last_ack_ms = apply_ack_events(
        state.match_index, state.last_ack_ms, slot, ev_peer, ev_match,
        ev_time_ms, ev_valid)
    is_leader = state.role == ROLE_LEADER
    cu = update_commit(match_index, state.self_mask, flush_index,
                       state.conf_cur, state.conf_old, state.commit_index,
                       state.first_leader_index, is_leader)
    timeouts = election_timeout(now_ms, election_deadline_ms,
                                state.role == ROLE_FOLLOWER)
    stale = check_leadership(last_ack_ms, state.self_mask, state.conf_cur,
                             state.conf_old, now_ms, leadership_timeout_ms,
                             is_leader)
    no_deadline = jnp.array(jnp.iinfo(election_deadline_ms.dtype).max,
                            election_deadline_ms.dtype)
    out_state = state._replace(
        match_index=match_index,
        last_ack_ms=last_ack_ms,
        flush_index=flush_index,
        commit_index=cu.new_commit,
        election_deadline_ms=jnp.where(timeouts, no_deadline,
                                       election_deadline_ms))
    out = jnp.stack([cu.new_commit, cu.changed.astype(jnp.int32),
                     timeouts.astype(jnp.int32), stale.astype(jnp.int32)])
    return ResidentFastStep(out_state, out)


def engine_step_resident_fast_sliced(state: DeviceState,
                                     ev_packed: jax.Array,
                                     meta: jax.Array) -> ResidentFastStep:
    """Slice-local variant of :func:`engine_step_resident_fast` for mesh
    deployments: the group batch is split into S contiguous slices and the
    packed events arrive PRE-ROUTED per slice.

    ``ev_packed`` is int32 [7, S, E] with the same row meaning as the flat
    fast step, except row 0 holds the SLICE-LOCAL row index (global slot =
    slice * (G // S) + local row).  Under ``parallel.mesh`` shardings each
    device owns one slice of the state AND the matching [7, 1, E] event
    plane, so a device's ack scatter only ever touches rows and event
    columns it holds locally — the replicated-events path made every
    device scan the full event batch, which is pure overhead at mesh
    scale.  vmap over the slice axis keeps the locality structural:
    XLA's SPMD partitioner sees a batched row-local program and emits
    zero collectives.

    With S == 1 this computes bit-identically to the flat fast step on
    the same events (enforced by tests/test_parallel.py).
    """
    n_slices = ev_packed.shape[1]
    sliced = state._replace(**{
        f: a.reshape((n_slices, a.shape[0] // n_slices) + a.shape[1:])
        for f, a in zip(state._fields, state)})
    r = jax.vmap(engine_step_resident_fast, in_axes=(0, 1, None))(
        sliced, ev_packed, meta)
    out_state = state._replace(**{
        f: a.reshape((-1,) + a.shape[2:])
        for f, a in zip(r.state._fields, r.state)})
    # [S, 4, Gs] -> [4, G]; slice blocks are contiguous in the group axis,
    # so this is a relabel, not a shuffle, under block sharding.
    out = jnp.swapaxes(r.out, 0, 1).reshape(4, -1)
    return ResidentFastStep(out_state, out)


def apply_vote_events(grants: jax.Array, rejects: jax.Array,
                      ev_group: jax.Array, ev_peer: jax.Array,
                      ev_granted: jax.Array, ev_valid: jax.Array
                      ) -> tuple[jax.Array, jax.Array]:
    """Scatter a packed batch of vote replies into grant/reject masks.

    First reply wins (the reference ignores duplicates,
    LeaderElection.waitForResults responses.putIfAbsent): an event for a peer
    that already replied in this round is dropped, so a retransmitted or
    flip-flopped reply can never mark a peer as both granting and rejecting.
    The host-side packer must additionally dedupe (group, peer) WITHIN one
    batch (keep the first) — two same-peer events in a single batch would
    otherwise both pass this gate.
    """
    g = jnp.where(ev_valid, ev_group, 0)
    p = jnp.where(ev_valid, ev_peer, 0)
    already = (grants | rejects)[g, p]
    ok = ev_valid & ~already
    new_grants = grants.at[g, p].max(ok & ev_granted, mode="drop")
    new_rejects = rejects.at[g, p].max(ok & ~ev_granted, mode="drop")
    return new_grants, new_rejects
