"""Packed deadline math for the host upkeep plane (no reference analog).

The per-group host bookkeeping — heartbeat next-due deadlines, hibernation
backstop clocks, retry-cache/WriteIndexCache expiry waterlines, client-window
idle sweeps, and watch-frontier dirty marks — lives in one dense
``[capacity, N_CHANNELS]`` float64 array per loop shard
(``server/upkeep.py``).  Each slow tick is then a single vectorized
``deadlines <= now`` compare + ``nonzero`` scan that yields only the due
slots, instead of a G-length Python loop over ``server.divisions``.

This is deliberately host-side numpy, not a device kernel: the arrays are
small (8 bytes x 5 channels per group), the compare is memory-bound, and
the dispatch targets are Python coroutines — shipping the compare through
XLA would round-trip for no win.  The packed layout, however, matches the
engine's ledger arrays slot-for-slot, which is what ROADMAP item 1 (pjit
mesh sharding) will shard.

Times are ``time.monotonic()`` seconds; an unarmed channel holds
``NO_DEADLINE`` (+inf), which can never compare due.
"""

from __future__ import annotations

import numpy as np

NO_DEADLINE = np.inf

# Channel layout of the packed deadline array.
CH_HEARTBEAT = 0   # leader heartbeat next-due (min over appenders)
CH_HIBERNATE = 1   # asleep-leader backstop refresh clock
CH_CACHE = 2       # retry-cache / WriteIndexCache oldest-expiry waterline
CH_WINDOW = 3      # client-window idle sweep
CH_WATCH = 4       # watch-frontier dirty mark (0.0 = dirty, inf = clean)
N_CHANNELS = 5

CHANNEL_NAMES = ("heartbeat", "hibernate", "cache", "window", "watch")


def new_deadlines(capacity: int) -> np.ndarray:
    """Fresh packed deadline array, every channel unarmed."""
    return np.full((capacity, N_CHANNELS), NO_DEADLINE, dtype=np.float64)


def due_scan(deadlines: np.ndarray, now: float) -> np.ndarray:
    """Slots with ANY channel due: one compare + one reduction + one
    nonzero over the packed array.  Returns sorted slot indices."""
    return np.nonzero((deadlines <= now).any(axis=1))[0]


def due_scan_min(row_min: np.ndarray, now: float) -> np.ndarray:
    """``due_scan`` against a maintained per-slot min-deadline vector
    (``[capacity]``): one compare + one nonzero over N floats instead of
    N x N_CHANNELS.  The plane keeps ``row_min`` incrementally current on
    every deadline write (O(N_CHANNELS) per write), which is what makes
    the per-tick scan overhead-bound rather than element-bound — measured
    < 3x thread-CPU growth for 16x more idle groups (tests/test_upkeep)."""
    return np.nonzero(row_min <= now)[0]


def due_channels(deadlines: np.ndarray, slots: np.ndarray, now: float
                 ) -> np.ndarray:
    """Per-slot boolean [len(slots), N_CHANNELS] due mask for the slots a
    ``due_scan`` surfaced (only the due rows are re-compared)."""
    return deadlines[slots] <= now


def next_wake(deadlines: np.ndarray) -> float:
    """Earliest armed deadline across every slot and channel
    (NO_DEADLINE when fully idle) — the tick driver may sleep until it."""
    if deadlines.size == 0:
        return NO_DEADLINE
    return float(deadlines.min())


def reference_due(deadlines: np.ndarray, now: float) -> list[int]:
    """Scalar Python walk with the same semantics as ``due_scan`` — the
    per-group loop the plane replaces, kept as the equivalence oracle for
    the randomized tests and the scaling baseline."""
    due = []
    for slot in range(deadlines.shape[0]):
        for ch in range(deadlines.shape[1]):
            if deadlines[slot, ch] <= now:
                due.append(slot)
                break
    return due
