from ratis_tpu.retry.policies import (ClientRetryEvent, ExceptionDependentRetry,
                                      ExponentialBackoffRetry, MultipleLinearRandomRetry,
                                      RequestTypeDependentRetryPolicy, RetryAction,
                                      RetryLimited, RetryPolicies, RetryPolicy)
