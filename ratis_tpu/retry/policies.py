"""Client retry policies.

Capability parity with the reference's retry package
(ratis-common/src/main/java/org/apache/ratis/retry/RetryPolicies.java,
ExponentialBackoffRetry.java, MultipleLinearRandomRetry.java,
ExceptionDependentRetry.java) and the client-side
RequestTypeDependentRetryPolicy (ratis-client/.../retry/).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from ratis_tpu.util.timeduration import TimeDuration


@dataclasses.dataclass(frozen=True)
class ClientRetryEvent:
    """What happened on one failed attempt, fed to the policy."""

    attempt_count: int
    cause: Optional[BaseException] = None
    request: object = None


@dataclasses.dataclass(frozen=True)
class RetryAction:
    should_retry: bool
    sleep_time: TimeDuration = TimeDuration.ZERO


class RetryPolicy:
    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        raise NotImplementedError

    def __str__(self) -> str:
        return type(self).__name__


class _NoRetry(RetryPolicy):
    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        return RetryAction(False)


class _RetryForeverNoSleep(RetryPolicy):
    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        return RetryAction(True)


@dataclasses.dataclass(frozen=True)
class RetryForeverWithSleep(RetryPolicy):
    sleep_time: TimeDuration

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        return RetryAction(True, self.sleep_time)

    def __str__(self) -> str:
        return f"RetryForeverWithSleep({self.sleep_time})"


@dataclasses.dataclass(frozen=True)
class RetryLimited(RetryPolicy):
    """retryUpToMaximumCountWithFixedSleep (RetryPolicies.java)."""

    max_attempts: int
    sleep_time: TimeDuration

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        if event.attempt_count >= self.max_attempts:
            return RetryAction(False)
        return RetryAction(True, self.sleep_time)

    def __str__(self) -> str:
        return f"RetryLimited(maxAttempts={self.max_attempts}, sleepTime={self.sleep_time})"


@dataclasses.dataclass(frozen=True)
class ExponentialBackoffRetry(RetryPolicy):
    """Randomized exponential backoff (reference ExponentialBackoffRetry.java):
    sleep ~ U(0.5, 1.5) * base * 2^attempt, capped at max_sleep."""

    base_sleep: TimeDuration
    max_sleep: Optional[TimeDuration] = None
    max_attempts: int = 0x7FFFFFFF

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        if event.attempt_count >= self.max_attempts:
            return RetryAction(False)
        exp = min(event.attempt_count, 30)
        sleep = self.base_sleep.seconds * (2 ** exp) * (0.5 + random.random())
        if self.max_sleep is not None:
            sleep = min(sleep, self.max_sleep.seconds)
        return RetryAction(True, TimeDuration(sleep))


@dataclasses.dataclass(frozen=True)
class MultipleLinearRandomRetry(RetryPolicy):
    """N1 attempts ~sleep T1, then N2 attempts ~sleep T2, ... with +/-50%
    randomization (reference MultipleLinearRandomRetry.java).  Built from a
    string like '1ms,10, 2ms,20'."""

    pairs: tuple[tuple[int, TimeDuration], ...]  # (count, sleep)

    @staticmethod
    def parse_comma_separated(s: str) -> "MultipleLinearRandomRetry":
        parts = [x.strip() for x in s.split(",") if x.strip()]
        if len(parts) % 2 != 0 or not parts:
            raise ValueError(f"even number of elements required: {s!r}")
        pairs = []
        for i in range(0, len(parts), 2):
            sleep = TimeDuration.valueOf(parts[i])
            count = int(parts[i + 1])
            pairs.append((count, sleep))
        return MultipleLinearRandomRetry(tuple(pairs))

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        n = event.attempt_count
        for count, sleep in self.pairs:
            if n < count:
                ms = sleep.to_ms() * (0.5 + random.random())
                return RetryAction(True, TimeDuration.millis(ms))
            n -= count
        return RetryAction(False)


class ExceptionDependentRetry(RetryPolicy):
    """Dispatch to a policy by exception type (ExceptionDependentRetry.java)."""

    def __init__(self, default_policy: RetryPolicy,
                 exception_policies: dict[type, RetryPolicy],
                 max_attempts: Optional[int] = None):
        self._default = default_policy
        self._map = dict(exception_policies)
        self._max_attempts = max_attempts

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        if self._max_attempts is not None and event.attempt_count >= self._max_attempts:
            return RetryAction(False)
        policy = self._default
        if event.cause is not None:
            for cls in type(event.cause).__mro__:
                if cls in self._map:
                    policy = self._map[cls]
                    break
        return policy.handle_attempt_failure(event)


class RequestTypeDependentRetryPolicy(RetryPolicy):
    """Dispatch to a policy (and optional timeout) by client request type
    (reference ratis-client/.../retry/RequestTypeDependentRetryPolicy.java)."""

    def __init__(self, default_policy: RetryPolicy,
                 type_policies: Optional[dict] = None,
                 type_timeouts: Optional[dict] = None):
        self._default = default_policy
        self._policies = dict(type_policies or {})
        self._timeouts = dict(type_timeouts or {})

    def timeout_for(self, request_type):
        return self._timeouts.get(request_type)

    def handle_attempt_failure(self, event: ClientRetryEvent) -> RetryAction:
        policy = self._default
        req = event.request
        if req is not None:
            policy = self._policies.get(req.type.type, self._default)
        return policy.handle_attempt_failure(event)


class RetryPolicies:
    RETRY_FOREVER_NO_SLEEP = _RetryForeverNoSleep()
    NO_RETRY = _NoRetry()

    @staticmethod
    def retry_forever_no_sleep() -> RetryPolicy:
        return RetryPolicies.RETRY_FOREVER_NO_SLEEP

    @staticmethod
    def no_retry() -> RetryPolicy:
        return RetryPolicies.NO_RETRY

    @staticmethod
    def retry_forever_with_sleep(sleep) -> RetryPolicy:
        return RetryForeverWithSleep(TimeDuration.valueOf(sleep))

    @staticmethod
    def retry_up_to_maximum_count_with_fixed_sleep(max_attempts: int, sleep) -> RetryPolicy:
        return RetryLimited(max_attempts, TimeDuration.valueOf(sleep))
