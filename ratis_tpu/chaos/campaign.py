"""Chaos campaign driver: N scenarios on ONE cluster, sequentially, as a
standing gate — and the ``chaos_1024`` bench rung.

``run_campaign`` builds a single ChaosCluster, runs each scenario's
fault schedule + SLO verification on it (healing in between), and folds
the results into one summary: scenarios passed, the worst re-election
convergence observed, and the recovery-throughput fraction (the
campaign's "how much does a fault cost once healed" number).  Every
injected fault and its recovery is journaled through the live servers'
watchdog ``/events`` plane, so a scrape mid-campaign shows the faults
interleaved with whatever they organically triggered (commit-stall,
election-churn, follower-lag, stuck-lane).

``run_chaos_1024`` is the bench rung (ROADMAP open item 5): the default
campaign at the 1024-group batched shape — where the windowed-rewind and
packed-ack paths actually live — with durable logs so the
slow-disk fault bites a real fsync path (the 1024-group rung runs the
shared interleaved store, ``raft.tpu.log.shared``).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties
from ratis_tpu.chaos.scenario import run_scenario
from ratis_tpu.chaos.scenarios import build_scenario

LOG = logging.getLogger(__name__)

# The standing campaign: >= 6 distinct fault classes.  slow_disk is
# appended only on durable clusters (memory logs never reach the sync
# path, and a scenario that cannot bite must not count as passed).
DEFAULT_CAMPAIGN = ("partition_minority", "partition_leader",
                    "asymmetric_partition", "link_degraded",
                    "crash_restart_follower", "crash_restart_leader",
                    "leader_churn_storm", "slow_follower",
                    "grey_follower", "rebalance_storm")
DURABLE_EXTRA = ("slow_disk", "shared_log_tail_loss")


async def run_campaign(num_servers: int = 3, num_groups: int = 1,
                       seed: int = 0,
                       scenarios: Optional[tuple] = None,
                       transport: str = "sim", sm: str = "recording",
                       storage_root: Optional[str] = None,
                       writers: int = 3, active_groups: Optional[int] = None,
                       convergence_s: Optional[float] = None,
                       recovery_s: Optional[float] = None,
                       artifact_dir: Optional[str] = None,
                       extra_config: Optional[dict] = None,
                       extra_props: Optional[dict] = None) -> dict:
    """Run the scenario list on one cluster; returns the campaign
    summary dict (JSON-safe, the bench rung's RESULT payload)."""
    from ratis_tpu.conf.keys import RaftServerConfigKeys
    durable = storage_root is not None
    names = scenarios or (DEFAULT_CAMPAIGN
                          + (DURABLE_EXTRA if durable else ()))
    props = chaos_properties(num_groups, seed=seed)
    for k, v in (extra_props or {}).items():
        props.set(k, str(v))
    if convergence_s is None:
        convergence_s = RaftServerConfigKeys.Chaos.convergence_timeout(
            props).seconds
    if recovery_s is None:
        recovery_s = RaftServerConfigKeys.Chaos.recovery_timeout(
            props).seconds
    if artifact_dir:
        props.set(RaftServerConfigKeys.Chaos.ARTIFACT_DIR_KEY, artifact_dir)
    cluster = ChaosCluster(num_servers, num_groups, properties=props,
                           transport=transport, sm=sm,
                           storage_root=storage_root, seed=seed)
    config = {"servers": num_servers, "groups": num_groups, "sm": sm,
              "transport": transport, "writers": writers,
              "durable": durable,
              "active_groups": (active_groups
                                or min(num_groups, 8)),
              "convergence_s": convergence_s, "recovery_s": recovery_s}
    config.update(extra_config or {})
    t0 = time.monotonic()
    out: dict = {"seed": seed, "groups": num_groups,
                 "servers": num_servers, "transport": transport,
                 "scenarios": {}, "passed": 0, "total": len(names)}
    await cluster.start()
    bring_up_s = time.monotonic() - t0
    try:
        worst_reelect = 0.0
        fracs: list[float] = []
        for name in names:
            scenario = build_scenario(name, seed, config)
            t_s = time.monotonic()
            result = await run_scenario(cluster, scenario,
                                        artifact_dir=artifact_dir)
            entry = {"passed": result.passed,
                     "reelect_s": result.slos.get("reelect_s"),
                     "recovery_frac": result.recovery_frac,
                     "acked": result.acked,
                     "elapsed_s": round(time.monotonic() - t_s, 1)}
            if result.error:
                entry["error"] = result.error[:200]
            out["scenarios"][name] = entry
            if result.passed:
                out["passed"] += 1
                if result.slos.get("reelect_s"):
                    worst_reelect = max(worst_reelect,
                                        result.slos["reelect_s"])
                if result.recovery_frac:
                    fracs.append(result.recovery_frac)
            LOG.warning("chaos scenario %s seed=%s: %s (reelect %ss, "
                        "recovery x%s)", name, seed,
                        "PASS" if result.passed else
                        f"FAIL: {result.error}",
                        result.slos.get("reelect_s"), result.recovery_frac)
            # inter-scenario settle: the next schedule's baseline window
            # must not start inside this one's tail turbulence.  A
            # settle failure is DATA (the scenario already recorded its
            # own verdict) — one wedged scenario must not vaporize the
            # rest of the campaign's results
            try:
                await cluster.wait_all_leaders(timeout=convergence_s)
                await cluster.wait_quiesced(timeout=recovery_s)
            except TimeoutError as e:
                entry["settle_failed"] = str(e)[:200]
                LOG.warning("chaos campaign: cluster did not settle "
                            "after %s: %s", name, e)
        out["worst_reelect_s"] = round(worst_reelect, 3)
        out["recovery_frac"] = (round(min(fracs), 3) if fracs else 0.0)
        out["bring_up_s"] = round(bring_up_s, 1)
        out["elapsed_s"] = round(time.monotonic() - t0, 1)
        # the /events flight recorder: every injected fault must have
        # been journaled (and paired on success) on some live server
        events = [e for s in cluster.servers.values()
                  if s.watchdog is not None for e in s.watchdog.events()]
        out["fault_events"] = sum(1 for e in events
                                  if e["kind"] == "injected-fault")
        out["recovered_events"] = sum(1 for e in events
                                      if e["kind"] == "fault-recovered")
        out["organic_events"] = sum(
            1 for e in events
            if e["kind"] not in ("injected-fault", "fault-recovered",
                                 "rebalance", "rebalance-done"))
    finally:
        await cluster.close()
    return out


async def run_chaos_1024(seed: int = 0, num_groups: int = 1024,
                         transport: str = "sim",
                         storage_root: Optional[str] = None,
                         artifact_dir: Optional[str] = None) -> dict:
    """The ``chaos_1024`` bench rung: the default campaign at the
    1024-group batched shape with counter-oracle invariants (per group:
    ``acked <= counter <= attempts`` on every replica, replicas equal)
    and durable segmented logs so slow-disk is a real fsync fault.
    Density-scaled timeouts come from the bench cost model
    (tools/bench_cluster.bench_properties), so the campaign stresses
    exactly the configuration the perf rungs measure."""
    import tempfile
    own_tmp = None
    if storage_root is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ratis-chaos-")
        storage_root = own_tmp.name
    try:
        return await run_campaign(
            num_servers=3, num_groups=num_groups, seed=seed,
            transport=transport, sm="counter",
            storage_root=storage_root, writers=4,
            active_groups=min(num_groups, 64),
            artifact_dir=artifact_dir,
            # leader-targeted faults depose 1000+ leaderships at once
            # (the real blast radius of losing a leader-heavy server):
            # the bound covers the mass re-election plus drain
            convergence_s=120.0, recovery_s=240.0,
            # Storm containment at 2048 channels: 1s/2s election
            # timeouts were metastable under MASS deposal — the fault
            # surge re-fired timeouts faster than the vote storm could
            # drain (126 election-churn events, no quiesce in 240s) —
            # exactly the basin bench_properties documents for gRPC at
            # this density.  The campaign runs the same margin tier a
            # real deployment tunes; fault holds scale with it
            # (hold_scale) so partitions still outlast the timeout band
            # and re-election genuinely fires during the fault.
            # the chaos rung runs the SHARED log plane (round 12,
            # raft.tpu.log.shared): one interleaved segment sequence per
            # loop shard, so slow-disk and tail-loss faults hit the one
            # fsync stream every co-located group rides
            extra_props={"raft.server.rpc.timeout.min": "4s",
                         "raft.server.rpc.timeout.max": "8s",
                         "raft.tpu.log.shared": "1"},
            extra_config={"min_acked": 50, "recovery_window_s": 8.0,
                          "hold_scale": 6.0})
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
