"""The standing scenario library: named, seed-deterministic fault
schedules.

Every builder is a pure function of ``(seed, config)`` — two builds with
the same inputs yield byte-identical step tuples (asserted by
``tools/chaos_replay.py`` before a replay run, and by the engine tests).
Times and parameters draw from one ``random.Random(seed)`` so campaigns
explore a little differently per seed while staying exactly replayable.

The library covers the fault classes the reference's correctness story
rests on (RaftExceptionBaseTest, the kill/restart suites, leader-election
churn tests) plus the degraded-link shapes only the chaos link shim can
produce on real sockets.
"""

from __future__ import annotations

import random
import zlib
from typing import Callable, Optional

from ratis_tpu.chaos.faults import Step, make_step
from ratis_tpu.chaos.scenario import Scenario

# name -> builder(rng, config) -> tuple[Step, ...]
_BUILDERS: dict[str, Callable] = {}


def _scenario(name: str):
    def register(fn):
        _BUILDERS[name] = fn
        return fn
    return register


def scenario_names() -> list[str]:
    return sorted(_BUILDERS)


def build_scenario(name: str, seed: int,
                   config: Optional[dict] = None) -> Scenario:
    """Resolve ``name`` to its deterministic step schedule.  ``config``
    carries the cluster/load shape (servers, groups, sm, writers,
    durable, active_groups) and the SLO bounds (``convergence_s``,
    ``recovery_s``); builders read what they need from it."""
    if name not in _BUILDERS:
        raise ValueError(f"unknown chaos scenario {name!r}; "
                         f"known: {scenario_names()}")
    cfg = dict(config or {})
    cfg.setdefault("servers", 3)
    cfg.setdefault("groups", 1)
    cfg.setdefault("sm", "recording")
    cfg.setdefault("writers", 3)
    # crc32, not hash(): builtin str hashing is randomized per process,
    # and the whole point is that a replay in a NEW process derives the
    # byte-identical schedule from (name, seed, config)
    rng = random.Random((seed * 1_000_003) ^ zlib.crc32(name.encode()))
    steps = tuple(sorted(_BUILDERS[name](rng, cfg), key=lambda s: s.at_s))
    slos = {"convergence_s": float(cfg.get("convergence_s", 30.0)),
            "recovery_s": float(cfg.get("recovery_s", 60.0))}
    return Scenario(name=name, seed=seed, config=cfg, steps=steps,
                    slos=slos)


# The pre-fault window: every schedule leaves this much clean load up
# front so the recovery-throughput fraction has a baseline to divide by.
_WARM_S = 1.0


def _hold(cfg: dict, seconds: float) -> float:
    """Fault HOLD durations scale with the cluster's election-timeout
    tier (``hold_scale``): the small-cluster schedules assume 100-200ms
    election timeouts, and a campaign running the density-scaled 4s/8s
    tier must hold partitions PAST the timeout band or re-election never
    actually fires during the fault."""
    return round(seconds * float(cfg.get("hold_scale", 1.0)), 2)


@_scenario("partition_minority")
def _partition_minority(rng: random.Random, cfg: dict) -> tuple:
    """Partition a follower minority away, hold, heal: the healthy
    majority must keep committing throughout (no re-election at all) and
    the healed minority must catch up with zero lost acks."""
    hold = _hold(cfg, round(rng.uniform(1.0, 2.0), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    n = int(cfg.get("servers", 3))
    extra = max(0, (n - 1) // 2 - 1)  # minority = floor((n-1)/2) followers
    return (make_step(t, "partition", "follower:0",
                      extra_followers=extra),
            make_step(t + hold, "heal"))


@_scenario("partition_leader")
def _partition_leader(rng: random.Random, cfg: dict) -> tuple:
    """Isolate the leader completely: the rest must re-elect within the
    convergence bound, and writes acked by EITHER leader must survive
    exactly once (the classic split-brain probe)."""
    hold = _hold(cfg, round(rng.uniform(1.5, 2.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "partition", "leader"),
            make_step(t + hold, "heal"))


@_scenario("asymmetric_partition")
def _asymmetric_partition(rng: random.Random, cfg: dict) -> tuple:
    """One-directional blackhole: the leader can send to a follower but
    never hears its acks (or vice versa) — the shape that distinguishes
    ack-loss handling from plain disconnection."""
    hold = _hold(cfg, round(rng.uniform(1.0, 2.0), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    steps = [make_step(t, "block", "follower:0", dst="leader")]
    if rng.random() < 0.5:
        steps.append(make_step(t + 0.2, "block", "leader",
                               dst="follower:1"))
    steps.append(make_step(t + hold, "heal"))
    return tuple(steps)


@_scenario("link_degraded")
def _link_degraded(rng: random.Random, cfg: dict) -> tuple:
    """Latency + jitter + probabilistic drop on one follower's links —
    the gray-failure shape: nothing is down, everything is slow and
    lossy, and the windowed-rewind path earns its keep."""
    hold = _hold(cfg, round(rng.uniform(1.5, 2.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "link", "follower:0",
                      latency_ms=round(rng.uniform(5, 20), 1),
                      jitter_ms=round(rng.uniform(5, 15), 1),
                      drop_rate=round(rng.uniform(0.05, 0.2), 3)),
            make_step(t + hold, "heal"))


@_scenario("crash_restart_follower")
def _crash_restart_follower(rng: random.Random, cfg: dict) -> tuple:
    """Crash a follower mid-load and bring it back; with durable storage
    the restart loses a few tail entries (``truncate_tail``) so recovery
    exercises the INCONSISTENCY/rewind guard, not just a reconnect."""
    down = _hold(cfg, round(rng.uniform(0.8, 1.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    tail = int(cfg.get("truncate_tail",
                       rng.randint(1, 4) if cfg.get("durable") else 0))
    return (make_step(t, "kill", "follower:0"),
            make_step(t + down, "restart", truncate_tail=tail))


@_scenario("crash_restart_leader")
def _crash_restart_leader(rng: random.Random, cfg: dict) -> tuple:
    """Crash the LEADER mid-load: acked writes must survive the
    succession, the old leader rejoins as a follower and catches up."""
    down = _hold(cfg, round(rng.uniform(1.0, 1.8), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "kill", "leader"),
            make_step(t + down, "restart"))


@_scenario("leader_churn_storm")
def _leader_churn_storm(rng: random.Random, cfg: dict) -> tuple:
    """Repeated brief leader isolations — the churn storm that deposed
    thousands of leaders in perf rounds 4-5.  Every isolation forces a
    succession; the SLO is that the LAST heal converges in bound with
    nothing lost across any of the handovers."""
    steps = []
    t = _WARM_S
    for _ in range(int(cfg.get("churn_rounds", 3))):
        t += rng.uniform(0.1, 0.4)
        steps.append(make_step(t, "partition", "leader"))
        t += _hold(cfg, rng.uniform(0.8, 1.5))
        steps.append(make_step(t, "heal"))
        t += _hold(cfg, rng.uniform(0.5, 1.0))  # successor settles
    return tuple(steps)


@_scenario("slow_follower")
def _slow_follower(rng: random.Random, cfg: dict) -> tuple:
    """Delay one follower's append handling (the APPEND_ENTRIES injection
    point): commits must keep flowing through the other majority and the
    laggard must drain its backlog after the heal."""
    hold = _hold(cfg, round(rng.uniform(1.5, 2.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "slow_follower", "follower:0",
                      delay_ms=int(rng.uniform(30, 80))),
            make_step(t + hold, "heal"))


@_scenario("slow_disk")
def _slow_disk(rng: random.Random, cfg: dict) -> tuple:
    """Delay one server's log-sync batches (the LOG_SYNC injection point
    in the shared per-device LogWorker): every co-hosted group pays the
    degraded device, exactly like a real slow disk.  Durable logs only —
    memory-log clusters never reach the sync path."""
    hold = _hold(cfg, round(rng.uniform(1.5, 2.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "slow_disk", "follower:0",
                      delay_ms=int(rng.uniform(20, 60))),
            make_step(t + hold, "heal"))


@_scenario("randomized_nemesis")
def _randomized_nemesis(rng: random.Random, cfg: dict) -> tuple:
    """The classic randomized nemesis (the old tests/test_chaos.py loop,
    now a deterministic SCHEDULE): kills/restarts, partitions, and
    asymmetric blackholes drawn from the seed over ``duration_s``.  The
    kill branch fires at EVERY cluster size (the old in-test nemesis
    silently no-opped its kill arm off 3 servers) but never takes a
    second server down before the first restarts — the nemesis probes
    recovery, it does not destroy quorum."""
    n = int(cfg.get("servers", 3))
    duration = float(cfg.get("duration_s", 6.0))
    steps = []
    t = _WARM_S
    while t < _WARM_S + duration:
        t += rng.uniform(0.4, 0.9)
        fault = rng.random()
        if fault < 0.4:
            victim = f"server:{rng.randrange(n)}"
            steps.append(make_step(t, "kill", victim))
            t += rng.uniform(0.4, 0.9)
            steps.append(make_step(t, "restart"))
        elif fault < 0.8:
            steps.append(make_step(t, "partition",
                                   f"server:{rng.randrange(n)}"))
            t += rng.uniform(0.3, 0.9)
            steps.append(make_step(t, "heal"))
        else:
            a = rng.randrange(n)
            b = (a + 1 + rng.randrange(n - 1)) % n
            steps.append(make_step(t, "block", f"server:{a}",
                                   dst=f"server:{b}"))
            t += rng.uniform(0.2, 0.5)
            steps.append(make_step(t, "heal"))
    return tuple(steps)


@_scenario("shared_log_tail_loss")
def _shared_log_tail_loss(rng: random.Random, cfg: dict) -> tuple:
    """Round-12 shared log plane: crash a follower and chop the tail of
    its per-shard INTERLEAVED segment sequence (raft.tpu.log.shared) —
    one lost write-back cache rewinds an arbitrary subset of the
    shard's groups at once, entries and control records alike.  The
    boot scan must rebuild every hosted group from the short stream and
    the leaders must rewind each one forward; zero acked writes lost,
    exactly-once apply."""
    down = _hold(cfg, round(rng.uniform(0.8, 1.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    # the chop interleaves many groups, so take a deeper tail than the
    # per-group scenarios — every record removed hits a different group
    tail = int(cfg.get("truncate_tail", rng.randint(8, 24)))
    return (make_step(t, "kill", "follower:0"),
            make_step(t + down, "restart", truncate_tail=tail))


@_scenario("overload_shed")
def _overload_shed(rng: random.Random, cfg: dict) -> tuple:
    """Serving-plane overload (round 12): degrade the follower links so
    commits slow to a crawl while writers keep pushing — the leader's
    intake backs past its per-shard pending budget
    (raft.tpu.serving.admission.*) and admission control must shed the
    overflow with TYPED overload replies.  SLO = the usual zero lost
    acks + exactly-once, plus (with ``expect_shed`` in the config) that
    shedding actually happened and every unacked attempt surfaced as a
    typed reply, not a silent client timeout — bounded pending, not p99
    collapse."""
    hold = _hold(cfg, round(rng.uniform(1.5, 2.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    # BOTH followers degraded: with one slow follower the other still
    # completes the majority at full speed and nothing ever queues
    return (make_step(t, "link", "follower:0",
                      latency_ms=round(rng.uniform(40, 80), 1),
                      jitter_ms=round(rng.uniform(5, 15), 1),
                      drop_rate=0.0),
            make_step(t + 0.1, "link", "follower:1",
                      latency_ms=round(rng.uniform(40, 80), 1),
                      jitter_ms=round(rng.uniform(5, 15), 1),
                      drop_rate=0.0),
            make_step(t + hold, "heal"))


@_scenario("grey_follower")
def _grey_follower(rng: random.Random, cfg: dict) -> tuple:
    """Grey failure (the lag-ledger detector's reason to exist): heavy
    latency + jitter on ONE follower's links, zero drop — every link
    stays up and acking, quorum commits through the other follower, and
    the victim silently falls behind on every group at once.  The run
    must raise KIND_GREY_FOLLOWER (paired with its grey-recovered close
    after the heal) on top of the usual zero-lost-acks / exactly-once
    oracle.  ``expect_grey`` arms the runner: detector thresholds are
    retuned live for the scenario's write rates (grey_lag_entries /
    grey_fraction / grey_min_groups / grey_rounds / grey_up_window_ms
    in the config override the armed values) and restored afterwards.
    Load is concentrated (``active_groups``) so per-group commit deltas
    stay visibly nonzero within each ledger pass — an idle group's links
    never count as active and can never vote grey."""
    cfg["expect_grey"] = True
    cfg["active_groups"] = min(int(cfg.get("active_groups", 8) or 8), 8)
    hold = _hold(cfg, round(rng.uniform(2.5, 3.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    return (make_step(t, "link", "follower:0",
                      latency_ms=round(rng.uniform(250, 400), 1),
                      jitter_ms=round(rng.uniform(40, 80), 1),
                      drop_rate=0.0),
            make_step(t + hold, "heal"))


@_scenario("rebalance_storm")
def _rebalance_storm(rng: random.Random, cfg: dict) -> tuple:
    """Placement controller under fire (``expect_rebalance`` arms a
    PlacementController per server with storm thresholds: zero
    hysteresis, near-zero hot-share, sub-second rounds): moderate
    latency on one follower's links makes it score grey/laggy (steering
    fires) while the skewed write load keeps the hot set moving (so
    transfer actuations race the faults), then a SECOND follower crashes
    and restarts mid-storm — quorum survives through the leader plus the
    slow follower, and every controller actuation (including any a dying
    transfer aborted) must land with its rebalance-done pair.  SLO = the
    usual zero lost acks + exactly-once + convergence, plus the pairing
    check.  Load stays concentrated so commit deltas register in every
    ledger pass, same as grey_follower."""
    cfg["expect_rebalance"] = True
    cfg["active_groups"] = min(int(cfg.get("active_groups", 8) or 8), 8)
    hold = _hold(cfg, round(rng.uniform(2.5, 3.5), 2))
    t = _WARM_S + rng.uniform(0, 0.3)
    down = _hold(cfg, round(rng.uniform(0.8, 1.4), 2))
    return (make_step(t, "link", "follower:0",
                      latency_ms=round(rng.uniform(150, 250), 1),
                      jitter_ms=round(rng.uniform(20, 50), 1),
                      drop_rate=0.0),
            make_step(t + 0.6, "kill", "follower:1"),
            make_step(t + 0.6 + down, "restart"),
            make_step(t + hold, "heal"))


@_scenario("window_crash")
def _window_crash(rng: random.Random, cfg: dict) -> tuple:
    """Round-9 window-protocol recovery: slow a follower so depth>1
    append frames pile onto its lanes, crash it mid-window, restart with
    a truncated durable tail — the sender must re-cut lanes
    (lane_resets), rewind through INCONSISTENCY (windowed_rewinds), and
    lose nothing."""
    t = _WARM_S + rng.uniform(0, 0.2)
    slow_ms = int(cfg.get("slow_ms", 25))
    down = _hold(cfg, round(rng.uniform(0.8, 1.2), 2))
    return (make_step(t, "slow_follower", "follower:0", delay_ms=slow_ms),
            make_step(t + 0.8, "kill", "follower:0"),
            make_step(t + 0.8 + down, "restart",
                      truncate_tail=int(cfg.get("truncate_tail", 3))),
            make_step(t + 1.0 + down, "heal"))
