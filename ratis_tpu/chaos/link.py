"""Transport link-fault shim: partitions and degraded links on EVERY
transport, not just the simulated hub's block matrix.

The simulated transport always had per-direction blocking
(SimulatedNetwork.block, cf. the reference's
MiniRaftCluster.RpcBase.setBlockRequestsFrom) — but nothing could
partition or degrade a link over the real TCP/gRPC sockets, so the chaos
suite could never run at the shapes where the pipelined-window and
packed-ack paths actually live.  This module is the transport-agnostic
fault plane: a process-wide table of directed ``(src, dst)`` link faults
(blackhole, latency+jitter, probabilistic drop) that every server
transport consults at its server-RPC send point when the server runs
with ``raft.tpu.chaos.enabled`` (unset — the default — no transport ever
touches this module; one bool test per send when set).

Determinism: latency jitter and drops draw from ONE seeded
``random.Random`` (:meth:`LinkFaultTable.reseed`), so a scenario's fault
behavior replays exactly for a given seed on the deterministic in-process
harness.  In-process test clusters share the table the way they share
the tracer and the injection registry.
"""

from __future__ import annotations

import asyncio
import random
from typing import NamedTuple, Optional

from ratis_tpu.protocol.exceptions import TimeoutIOException


class LinkFault(NamedTuple):
    """Fault state of one DIRECTED link (``None`` endpoint = wildcard)."""

    blocked: bool = False
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    drop_rate: float = 0.0

    def degraded(self) -> bool:
        return (self.blocked or self.latency_ms > 0 or self.jitter_ms > 0
                or self.drop_rate > 0)


def _norm(peer) -> Optional[str]:
    return None if peer is None else str(peer)


class LinkFaultTable:
    """Directed link faults keyed by ``(src, dst)`` peer-id strings.

    ``None`` acts as a wildcard on either side (matching the simulated
    hub's block semantics); the most specific entry wins:
    ``(src, dst)`` > ``(src, None)`` > ``(None, dst)`` > ``(None, None)``.
    """

    def __init__(self, seed: int = 0):
        self._faults: dict[tuple[Optional[str], Optional[str]], LinkFault] = {}
        self._rng = random.Random(seed)
        self.metrics = {"gated": 0, "dropped": 0, "blocked": 0,
                        "delayed": 0}

    # ----------------------------------------------------------- mutation

    def reseed(self, seed: int) -> None:
        """Reset the drop/jitter RNG — scenario replay determinism."""
        self._rng = random.Random(seed)

    def block(self, src=None, dst=None) -> None:
        """Blackhole src->dst (None = wildcard)."""
        self.set_link(src, dst, blocked=True)

    def set_link(self, src=None, dst=None, *, blocked: bool = False,
                 latency_ms: float = 0.0, jitter_ms: float = 0.0,
                 drop_rate: float = 0.0) -> None:
        self._faults[(_norm(src), _norm(dst))] = LinkFault(
            blocked, latency_ms, jitter_ms, drop_rate)

    def partition(self, side_a, side_b) -> None:
        """Full bidirectional partition between two peer sets."""
        for a in side_a:
            for b in side_b:
                self.block(a, b)
                self.block(b, a)

    def isolate(self, peer) -> None:
        """Blackhole everything to AND from ``peer``."""
        self.block(peer, None)
        self.block(None, peer)

    def heal(self, src=None, dst=None) -> None:
        self._faults.pop((_norm(src), _norm(dst)), None)

    def heal_all(self) -> None:
        self._faults.clear()

    # ------------------------------------------------------------ queries

    def __bool__(self) -> bool:
        return bool(self._faults)

    def lookup(self, src, dst) -> Optional[LinkFault]:
        if not self._faults:
            return None
        s, d = _norm(src), _norm(dst)
        for key in ((s, d), (s, None), (None, d), (None, None)):
            f = self._faults.get(key)
            if f is not None:
                return f
        return None

    def is_blocked(self, src, dst) -> bool:
        f = self.lookup(src, dst)
        return f is not None and f.blocked

    def active(self) -> list[dict]:
        """Active fault descriptors (the /health ``chaos`` payload)."""
        return [{"src": k[0], "dst": k[1], "blocked": f.blocked,
                 "latency_ms": f.latency_ms, "jitter_ms": f.jitter_ms,
                 "drop_rate": f.drop_rate}
                for k, f in sorted(self._faults.items(),
                                   key=lambda kv: (kv[0][0] or "",
                                                   kv[0][1] or ""))]

    # --------------------------------------------------------------- gate

    async def gate(self, src, dst) -> None:
        """Apply the directed link's fault to one RPC hop: raise
        :class:`TimeoutIOException` for a blackholed or dropped hop, sleep
        out the configured latency(+jitter) otherwise.  A no-op dict
        lookup when no fault covers the link."""
        f = self.lookup(src, dst)
        if f is None:
            return
        self.metrics["gated"] += 1
        if f.blocked:
            self.metrics["blocked"] += 1
            raise TimeoutIOException(f"chaos: link {src}->{dst} blackholed")
        if f.drop_rate > 0 and self._rng.random() < f.drop_rate:
            self.metrics["dropped"] += 1
            raise TimeoutIOException(f"chaos: link {src}->{dst} dropped")
        d = f.latency_ms
        if f.jitter_ms:
            d += self._rng.uniform(0, f.jitter_ms)
        if d > 0:
            self.metrics["delayed"] += 1
            await asyncio.sleep(d / 1e3)


# The process-wide table (shared by co-hosted in-process servers, like the
# tracer and the injection registry).  Transports consult it only when
# their server was built with raft.tpu.chaos.enabled.
_TABLE = LinkFaultTable()


def link_faults() -> LinkFaultTable:
    return _TABLE
