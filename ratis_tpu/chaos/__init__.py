"""Chaos campaign subsystem: deterministic, seed-replayable fault
scenarios as a standing correctness gate.

The observability plane (watchdog + ``/events``) can *see* failures; this
package *causes* them, on purpose and reproducibly:

- :mod:`ratis_tpu.chaos.link` — the transport link-fault shim: directed
  partitions, per-link latency/jitter/drop, consulted by the simulated,
  TCP, and gRPC transports when ``raft.tpu.chaos.enabled`` is set;
- :mod:`ratis_tpu.chaos.faults` — the typed fault-step vocabulary shared
  by scenarios, the runner, and replay artifacts;
- :mod:`ratis_tpu.chaos.cluster` — an in-process multi-group cluster
  harness with kill/restart (and tail log truncation on restart);
- :mod:`ratis_tpu.chaos.scenario` — the scenario runner: executes a
  seed-deterministic fault schedule under write load, journals every
  injected fault and its observed recovery through the watchdog
  ``/events`` plane, and asserts the recovery SLOs (re-election
  convergence bound, zero lost acks, exactly-once apply, catch-up);
- :mod:`ratis_tpu.chaos.scenarios` — the standing scenario library;
- :mod:`ratis_tpu.chaos.campaign` — the ``chaos_1024`` campaign rung.

A failing scenario emits a self-contained ``(seed, scenario, journal)``
artifact that ``python -m ratis_tpu.tools.chaos_replay`` re-runs exactly.

Reference analogs: RaftExceptionBaseTest, the kill/restart suites over
simulated RPC, and CodeInjectionForTesting
(ratis-common/.../util/CodeInjectionForTesting.java:29-60, mirrored by
``ratis_tpu.util.injection``).
"""

from ratis_tpu.chaos.link import LinkFaultTable, link_faults
from ratis_tpu.chaos.scenario import ScenarioResult, run_scenario
from ratis_tpu.chaos.scenarios import build_scenario, scenario_names

__all__ = ["LinkFaultTable", "link_faults", "ScenarioResult",
           "run_scenario", "build_scenario", "scenario_names"]
