"""Typed fault-step vocabulary shared by scenarios, the runner, and
replay artifacts.

A scenario is a SCHEDULE: a tuple of :class:`Step` records, each an
``(at_s, op, target, args)`` quadruple resolved deterministically from
the scenario seed at BUILD time.  Targets are symbolic (``"leader"``,
``"follower:0"``, ``"server:2"``) because the concrete leader is runtime
state; the schedule itself — what fault, against which role, when, with
which parameters — is a pure function of ``(scenario name, seed,
config)``, which is what makes a recorded campaign artifact replayable
bit-for-bit (``tools/chaos_replay.py`` re-derives the schedule and
asserts equality before re-running it).

Ops (applied by :class:`ratis_tpu.chaos.scenario.ScenarioRunner`):

========================  ====================================================
``partition``             full bidirectional partition; ``args["side"]`` is a
                          symbolic peer set (``"leader"`` / ``"minority"``)
``block``                 directed blackhole target -> ``args["dst"]``
                          (either side may be ``"*"``)
``link``                  degrade target's inbound links:
                          ``latency_ms`` / ``jitter_ms`` / ``drop_rate``
``kill``                  close the target server (crash)
``restart``               restart the most recently killed server;
                          ``args["truncate_tail"]`` drops that many entries
                          off every group's durable log tail first
``slow_disk``             delay the LOG_SYNC injection point on the target
                          server by ``args["delay_ms"]`` per flush batch
``slow_follower``         delay the APPEND_ENTRIES injection point on the
                          target server by ``args["delay_ms"]`` per append
``heal``                  clear every link fault and injection delay
========================  ====================================================
"""

from __future__ import annotations

import dataclasses
import pathlib
import re
from typing import Optional

OPS = ("partition", "block", "link", "kill", "restart", "slow_disk",
       "slow_follower", "heal")


@dataclasses.dataclass(frozen=True)
class Step:
    at_s: float  # offset from scenario start (deterministic from seed)
    op: str
    target: str = ""       # symbolic: leader / follower:<k> / server:<k>
    args: tuple = ()       # sorted (key, value) pairs — hashable + JSON-safe

    def arg(self, key: str, default=None):
        for k, v in self.args:
            if k == key:
                return v
        return default

    def to_json(self) -> dict:
        return {"at_s": self.at_s, "op": self.op, "target": self.target,
                "args": dict(self.args)}

    @staticmethod
    def from_json(d: dict) -> "Step":
        return Step(float(d["at_s"]), d["op"], d.get("target", ""),
                    tuple(sorted(d.get("args", {}).items())))


def make_step(at_s: float, op: str, target: str = "", **args) -> Step:
    if op not in OPS:
        raise ValueError(f"unknown chaos op {op!r}; known: {OPS}")
    return Step(round(float(at_s), 4), op, target,
                tuple(sorted(args.items())))


# --------------------------------------------------- tail log truncation

_CLOSED_RE = re.compile(r"^log_(\d+)-(\d+)$")
_OPEN_RE = re.compile(r"^log_inprogress_(\d+)$")


def truncate_log_tail(current_dir: "pathlib.Path | str",
                      entries: int) -> int:
    """Drop the last ``entries`` records off a CLOSED server's segmented
    log on disk (the crash-with-lost-tail fault: the process died before
    its final appends became durable, or the disk lost its write-back
    cache).  Operates on the ``current/`` storage directory of one group;
    returns how many records were actually removed.  Only whole records
    go — the file stays structurally valid, so recovery treats it as a
    short log, not a corrupt one (the INCONSISTENCY/rewind path, not the
    checksum path)."""
    from ratis_tpu.server.log.segmented import read_records
    d = pathlib.Path(current_dir)
    segs = []
    for f in d.iterdir():
        m = _CLOSED_RE.match(f.name) or _OPEN_RE.match(f.name)
        if m:
            segs.append((int(m.group(1)), f))
    segs.sort()
    removed = 0
    for _start, path in reversed(segs):
        if removed >= entries:
            break
        payloads, _good = read_records(path)
        keep = max(0, len(payloads) - (entries - removed))
        removed += len(payloads) - keep
        if keep == 0:
            path.unlink()
            continue
        # rebuild the file up to the kept prefix (records are
        # length-prefixed; re-walk to the keep'th record boundary)
        from ratis_tpu.server.log.segmented import (MAGIC, _REC_HDR)
        data = path.read_bytes()
        off = len(MAGIC)
        for _ in range(keep):
            ln, _crc = _REC_HDR.unpack_from(data, off)
            off += _REC_HDR.size + ln
        new_path = path
        m = _CLOSED_RE.match(path.name)
        if m:
            # a truncated closed segment's name must match its new end
            # index or recovery rejects it; reopen it as inprogress (the
            # shape a crashed writer leaves behind)
            new_path = path.with_name(f"log_inprogress_{m.group(1)}")
            path.rename(new_path)
        with open(new_path, "r+b") as fh:
            fh.truncate(off)
    return removed


def find_group_current_dirs(storage_root: "pathlib.Path | str"
                            ) -> list[pathlib.Path]:
    """Every group's ``current/`` log directory under one server's
    storage root (the truncation fan-out for multi-group servers)."""
    root = pathlib.Path(storage_root)
    if not root.exists():
        return []
    return sorted(p for p in root.glob("*/current") if p.is_dir())


# ------------------------------------------ shared log plane truncation

_SH_SEALED_RE = re.compile(r"^shared_(\d+)$")
_SH_OPEN_RE = re.compile(r"^shared_inprogress_(\d+)$")


def find_shared_shard_dirs(storage_root: "pathlib.Path | str"
                           ) -> list[pathlib.Path]:
    """Every per-shard interleaved segment directory under one server's
    storage root (``_sharedlog/shard-<k>``; raft.tpu.log.shared mode)."""
    root = pathlib.Path(storage_root)
    if not root.exists():
        return []
    return sorted(p for p in root.glob("_sharedlog/shard-*") if p.is_dir())


def truncate_shared_log_tail(shard_dir: "pathlib.Path | str",
                             records: int) -> int:
    """Drop the last ``records`` records off a CLOSED server's shared
    (interleaved) log shard on disk — the same lost-write-back-cache
    crash as :func:`truncate_log_tail`, but against the one per-shard
    segment sequence every co-located group appends into.  The chopped
    tail interleaves MANY groups' entries and control records, so one
    fault rewinds an arbitrary subset of the shard's groups at once.
    Only whole records go — recovery sees a short stream, not a torn
    one."""
    from ratis_tpu.server.log.segmented import MAGIC, _REC_HDR, read_records
    d = pathlib.Path(shard_dir)
    segs = []
    for f in d.iterdir():
        m = _SH_SEALED_RE.match(f.name) or _SH_OPEN_RE.match(f.name)
        if m:
            segs.append((int(m.group(1)), f))
    segs.sort()
    removed = 0
    for _n, path in reversed(segs):
        if removed >= records:
            break
        payloads, _good = read_records(path)
        keep = max(0, len(payloads) - (records - removed))
        removed += len(payloads) - keep
        if keep == 0:
            path.unlink()
            continue
        data = path.read_bytes()
        off = len(MAGIC)
        for _ in range(keep):
            ln, _crc = _REC_HDR.unpack_from(data, off)
            off += _REC_HDR.size + ln
        with open(path, "r+b") as fh:
            fh.truncate(off)
    return removed
