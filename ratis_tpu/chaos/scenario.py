"""Scenario runner: execute a seed-deterministic fault schedule under
write load, journal every fault and its observed recovery through the
watchdog ``/events`` plane, and assert the recovery SLOs.

A scenario is ``(name, seed, config, steps, slos)`` where ``steps`` is a
pure function of ``(name, seed, config)`` (see
:mod:`ratis_tpu.chaos.scenarios`) — which is what makes a failing run's
``(seed, scenario, journal)`` artifact replayable bit-for-bit by
``python -m ratis_tpu.tools.chaos_replay``.

SLOs asserted on every run:

- **re-election convergence**: after the last fault heals, every group
  has a READY leader within ``slos["convergence_s"]``
  (``raft.tpu.chaos.convergence-timeout`` supplies the campaign default);
- **zero lost acks**: every write the client saw ACKED is applied on
  every live replica — exactly once (the INCONSISTENCY/windowed-rewind
  guard is what this catches regressing);
- **exactly-once apply**: no payload applied twice anywhere (retry-cache
  dedupe across failover), and all replicas applied identical sequences;
- **catch-up under load**: replication + apply drain to the leader's
  commit on every replica within ``slos["recovery_s"]`` while writers
  are still running through the recovery window.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import pathlib
import time
from typing import Optional

from ratis_tpu.chaos.faults import Step
from ratis_tpu.chaos.link import link_faults
from ratis_tpu.protocol.exceptions import (RaftRetryFailureException,
                                           ResourceUnavailableException)
from ratis_tpu.server.watchdog import (KIND_FAULT_RECOVERED,
                                       KIND_INJECTED_FAULT)
from ratis_tpu.util import injection

LOG = logging.getLogger(__name__)

ARTIFACT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    seed: int
    config: dict           # cluster + load shape (JSON-safe)
    steps: tuple           # tuple[Step, ...] — deterministic from seed
    slos: dict             # {"convergence_s": .., "recovery_s": ..}

    def to_json(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "config": dict(self.config),
                "steps": [s.to_json() for s in self.steps],
                "slos": dict(self.slos)}


@dataclasses.dataclass
class ScenarioResult:
    name: str
    seed: int
    passed: bool = False
    error: Optional[str] = None
    slos: dict = dataclasses.field(default_factory=dict)    # measured
    checks: dict = dataclasses.field(default_factory=dict)  # invariants
    journal: list = dataclasses.field(default_factory=list)
    acked: int = 0
    attempts: int = 0
    baseline_cps: float = 0.0
    recovery_cps: float = 0.0
    # flight-recorder windows (one per telemetry-enabled server),
    # attached on failure: the samples + watchdog events spanning the
    # fault window ride inside the replay artifact
    flight: list = dataclasses.field(default_factory=list)

    @property
    def recovery_frac(self) -> float:
        """Recovery-window throughput as a fraction of the pre-fault
        baseline (1.0 = the fault cost nothing once healed)."""
        if self.baseline_cps <= 0:
            return 0.0
        return round(self.recovery_cps / self.baseline_cps, 3)

    def to_artifact(self, scenario: Scenario) -> dict:
        """Self-contained replay artifact: everything chaos_replay needs
        to re-run this scenario exactly and compare outcomes."""
        out = {"version": ARTIFACT_VERSION,
               "scenario": scenario.to_json(),
               "passed": self.passed, "error": self.error,
               "slos": self.slos, "checks": self.checks,
               "acked": self.acked, "attempts": self.attempts,
               "recovery_frac": self.recovery_frac,
               "journal": self.journal}
        if self.flight:
            out["flight"] = self.flight
        return out


def write_artifact(result: ScenarioResult, scenario: Scenario,
                   artifact_dir: "str | pathlib.Path") -> pathlib.Path:
    d = pathlib.Path(artifact_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"chaos-{scenario.name}-seed{scenario.seed}.json"
    path.write_text(json.dumps(result.to_artifact(scenario), indent=1,
                               sort_keys=True))
    return path


_RUN_IDS = __import__("itertools").count(1)


class _Writers:
    """The scenario's background write load: per-writer RaftClients with
    uniquely tagged payloads (recording mode) or counter INCREMENTs over
    a group sample (counter mode), every ack timestamped so the runner
    can report baseline vs recovery-window throughput.  Payloads carry a
    per-RUN tag so back-to-back scenarios on one long-lived cluster never
    collide in the recording oracle."""

    def __init__(self, cluster, config: dict, tag: str = ""):
        self.cluster = cluster
        self.tag = f"{tag}r{next(_RUN_IDS)}:"
        self.mode = config.get("sm", "recording")
        self.n_writers = int(config.get("writers", 3))
        self.active_groups = int(config.get("active_groups",
                                            min(cluster.num_groups, 8)))
        self.acked: list[bytes] = []
        self.ack_times: list[float] = []
        self.acked_per_group: dict = {}
        self.attempts_per_group: dict = {}
        self.attempts = 0
        # overload accounting (recording mode): a shed write surfaces as
        # a typed ResourceUnavailableException (possibly wrapped in a
        # retry-failure after the policy gives up) — a TIMEOUT means a
        # request was silently dropped, which the overload SLO forbids
        self.timeouts = 0
        self.shed_surfaced = 0
        # counter-oracle baseline: per-(gid, replica) counter value at run
        # start, so back-to-back scenarios on one cluster verify DELTAS
        self.counter_base: dict = {}
        self._stop = asyncio.Event()
        self._tasks: list[asyncio.Task] = []

    def snapshot_counters(self) -> None:
        if self.mode != "counter":
            return
        for g in self.cluster.groups[:self.active_groups]:
            for d in self.cluster.divisions(g.group_id):
                self.counter_base[(g.group_id,
                                   str(d.member_id.peer_id))] = \
                    d.state_machine.counter

    async def _recording_writer(self, wid: int) -> None:
        i = 0
        async with self.cluster.new_client() as client:
            while not self._stop.is_set():
                payload = f"{self.tag}w{wid}-{i}".encode()
                i += 1
                self.attempts += 1
                try:
                    reply = await asyncio.wait_for(
                        client.io().send(payload), 10.0)
                    if reply.success:
                        self.acked.append(payload)
                        self.ack_times.append(time.monotonic())
                    elif isinstance(reply.exception,
                                    ResourceUnavailableException):
                        self.shed_surfaced += 1
                except asyncio.TimeoutError:
                    self.timeouts += 1
                except RaftRetryFailureException as e:
                    if isinstance(e.cause, ResourceUnavailableException):
                        self.shed_surfaced += 1
                except Exception:
                    pass  # unacked: may or may not have committed
                await asyncio.sleep(0.002)

    async def _counter_writer(self, wid: int) -> None:
        from ratis_tpu.protocol.ids import ClientId
        client = self.cluster.factory.new_client_transport(
            self.cluster.properties)
        client_id = ClientId.random_id()
        gids = [g.group_id for g in
                self.cluster.groups[:self.active_groups]]
        j = wid
        try:
            while not self._stop.is_set():
                gid = gids[j % len(gids)]
                j += self.n_writers
                self.attempts += 1
                self.attempts_per_group[gid] = \
                    self.attempts_per_group.get(gid, 0) + 1
                ok = await self.cluster.write(gid, client=client,
                                              client_id=client_id,
                                              timeout=10.0)
                if ok:
                    self.acked_per_group[gid] = \
                        self.acked_per_group.get(gid, 0) + 1
                    self.ack_times.append(time.monotonic())
        finally:
            try:
                await client.close()
            except Exception:
                pass

    def start(self) -> None:
        writer = (self._counter_writer if self.mode == "counter"
                  else self._recording_writer)
        self._tasks = [asyncio.create_task(writer(w),
                                           name=f"chaos-writer-{w}")
                       for w in range(self.n_writers)]

    async def stop(self) -> None:
        self._stop.set()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()

    def rate_in(self, t0: float, t1: float) -> float:
        if t1 <= t0:
            return 0.0
        n = sum(1 for t in self.ack_times if t0 <= t < t1)
        return round(n / (t1 - t0), 2)

    @property
    def total_acked(self) -> int:
        return (len(self.acked) if self.mode != "counter"
                else sum(self.acked_per_group.values()))


class ScenarioRunner:
    """Drives one cluster through one scenario.  The runner owns the
    fault plane (link table + injection delays) and ALWAYS heals it —
    a crashed scenario must never leak faults into the next one."""

    def __init__(self, cluster, scenario: Scenario):
        self.cluster = cluster
        self.scenario = scenario
        self.result = ScenarioResult(scenario.name, scenario.seed)
        self._t0 = 0.0
        self._killed: list = []       # kill order (restart targets)
        self._slow_followers: dict[str, float] = {}
        self._slow_disks: dict[str, float] = {}
        self._fault_seq = 0

    # ----------------------------------------------------------- journal

    def _journal(self, kind: str, step: Optional[Step], detail: str,
                 fault_id: Optional[str] = None) -> str:
        fid = fault_id
        if fid is None:
            fid = (f"{self.scenario.name}/{self.scenario.seed}"
                   f"/{self._fault_seq}")
            self._fault_seq += 1
        record = {"t": round(time.monotonic() - self._t0, 3),
                  "kind": kind, "fault": fid, "detail": detail}
        if step is not None:
            record["op"] = step.op
            record["target"] = step.target
        self.result.journal.append(record)
        self.cluster.emit_fault_event(kind, detail, fid)
        return fid

    # ---------------------------------------------------- target resolve

    async def _resolve_peer(self, target: str):
        live = self.cluster.live_peer_ids()
        if target.startswith("server:"):
            return self.cluster.all_peer_ids()[int(target.split(":")[1])]
        if target == "leader" or target.startswith("follower:"):
            if self.cluster.num_groups > 1:
                # multi-group shape: roles are per GROUP, so "leader"
                # means the server CARRYING the leaderships (faulting it
                # deposes the fleet — the real leader-fault blast radius)
                # and "follower:k" a server carrying few or none —
                # resolving against group 0 alone once picked the
                # 1023-leadership server as a "follower" and turned a
                # follower-crash scenario into a full-fleet deposal
                counts = {p: sum(1 for d in s.divisions.values()
                                 if d.is_leader())
                          for p, s in self.cluster.servers.items()}
                ranked = sorted(counts, key=lambda p: (counts[p], str(p)))
                if target == "leader":
                    return ranked[-1]
                k = int(target.split(":")[1])
                followers = ranked[:-1] or ranked
                return followers[k % len(followers)]
            try:
                leader = await self.cluster.wait_for_leader(timeout=10.0)
                lead_id = leader.member_id.peer_id
            except TimeoutError:
                lead_id = live[0] if live else self.cluster.all_peer_ids()[0]
            if target == "leader":
                return lead_id
            k = int(target.split(":")[1])
            followers = [p for p in live if p != lead_id]
            return followers[k % len(followers)] if followers else lead_id
        from ratis_tpu.protocol.ids import RaftPeerId
        return RaftPeerId.value_of(target)

    # -------------------------------------------------------- injections

    def _arm_injections(self) -> None:
        slow_f, slow_d = self._slow_followers, self._slow_disks

        async def on_append(local_id, _remote_id, *_args):
            d = slow_f.get(str(local_id).split("@")[0])
            if d:
                await asyncio.sleep(d)

        async def on_sync(local_id, _remote_id, *_args):
            name = str(local_id)
            for victim, d in slow_d.items():
                if name.startswith(f"{victim}:") or name == victim:
                    await asyncio.sleep(d)
                    return

        injection.put(injection.APPEND_ENTRIES, on_append)
        injection.put(injection.LOG_SYNC, on_sync)

    def _disarm_injections(self) -> None:
        self._slow_followers.clear()
        self._slow_disks.clear()
        injection.remove(injection.APPEND_ENTRIES)
        injection.remove(injection.LOG_SYNC)

    # -------------------------------------------------------------- ops

    async def _apply_step(self, step: Step) -> None:
        faults = link_faults()
        if step.op == "partition":
            victim = await self._resolve_peer(step.target)
            side = [victim]
            extra = step.arg("extra_followers", 0)
            if extra:
                side += [p for p in self.cluster.live_peer_ids()
                         if p != victim][:extra]
            others = [p for p in self.cluster.all_peer_ids()
                      if p not in side]
            faults.partition(side, others)
            self._journal(KIND_INJECTED_FAULT, step,
                          f"partition {sorted(map(str, side))} | "
                          f"{sorted(map(str, others))}")
        elif step.op == "block":
            victim = await self._resolve_peer(step.target)
            dst = step.arg("dst", "*")
            dst_id = None if dst == "*" else await self._resolve_peer(dst)
            faults.block(victim, dst_id)
            self._journal(KIND_INJECTED_FAULT, step,
                          f"blackhole {victim}->{dst_id or '*'}")
        elif step.op == "link":
            victim = await self._resolve_peer(step.target)
            kw = dict(latency_ms=step.arg("latency_ms", 0.0),
                      jitter_ms=step.arg("jitter_ms", 0.0),
                      drop_rate=step.arg("drop_rate", 0.0))
            faults.set_link(None, victim, **kw)
            if step.arg("both", 1):
                faults.set_link(victim, None, **kw)
            self._journal(KIND_INJECTED_FAULT, step,
                          f"degrade links of {victim}: {kw}")
        elif step.op == "kill":
            victim = await self._resolve_peer(step.target)
            if victim in self.cluster.servers:
                await self.cluster.kill(victim)
                self._killed.append(victim)
                self._journal(KIND_INJECTED_FAULT, step, f"crash {victim}")
        elif step.op == "restart":
            if not self._killed:
                return
            victim = self._killed.pop(0)
            tail = step.arg("truncate_tail", 0)
            await self.cluster.restart(victim, truncate_tail=tail)
            self._journal(KIND_INJECTED_FAULT, step,
                          f"restart {victim}"
                          + (f" (tail -{tail} entries)" if tail else ""))
        elif step.op == "slow_disk":
            victim = await self._resolve_peer(step.target)
            self._slow_disks[str(victim)] = step.arg("delay_ms", 50) / 1e3
            self._journal(KIND_INJECTED_FAULT, step,
                          f"slow disk on {victim} "
                          f"(+{step.arg('delay_ms', 50)}ms/flush)")
        elif step.op == "slow_follower":
            victim = await self._resolve_peer(step.target)
            self._slow_followers[str(victim)] = \
                step.arg("delay_ms", 50) / 1e3
            self._journal(KIND_INJECTED_FAULT, step,
                          f"slow follower {victim} "
                          f"(+{step.arg('delay_ms', 50)}ms/append)")
        elif step.op == "heal":
            faults.heal_all()
            self._slow_followers.clear()
            self._slow_disks.clear()
            self._journal(KIND_INJECTED_FAULT, step, "heal all links")
        else:
            raise ValueError(f"unknown chaos op {step.op!r}")

    # -------------------------------------------------------------- run

    async def run(self) -> ScenarioResult:
        sc = self.scenario
        res = self.result
        link_faults().reseed(sc.seed)
        self._arm_injections()
        self._arm_grey()
        self._arm_rebalance()
        writers = _Writers(self.cluster, sc.config,
                           tag=f"{sc.name}.{sc.seed}.")
        # a quiesced start anchors the counter-delta oracle (and keeps a
        # previous scenario's in-flight tail out of this one's baseline)
        try:
            await self.cluster.wait_quiesced(timeout=sc.slos["recovery_s"])
        except TimeoutError:
            pass  # verified again (and enforced) after the heal
        writers.snapshot_counters()
        # shed baseline: back-to-back scenarios on one long-lived
        # cluster must assert THIS run's shedding, not the campaign's
        self._shed_base = self._shed_now()
        self._t0 = time.monotonic()
        writers.start()
        try:
            first_fault_at = min((s.at_s for s in sc.steps), default=0.0)
            for step in sorted(sc.steps, key=lambda s: s.at_s):
                delay = self._t0 + step.at_s - time.monotonic()
                if delay > 0:
                    await asyncio.sleep(delay)
                await self._apply_step(step)
            t_fault = self._t0 + first_fault_at

            # ------------------------------------------------------ heal
            t_heal = time.monotonic()
            link_faults().heal_all()
            self._disarm_injections()
            if self.cluster.network is not None:
                self.cluster.network.unblock_all()
            for victim in list(self._killed):
                self._killed.remove(victim)
                await self.cluster.restart(victim)
            # the storm's controllers go down WITH the faults: closing
            # here lets any in-flight actuation finish (or journal its
            # aborted pair) before the pairing SLO is checked
            await self._disarm_rebalance()

            # ---------------------------------- recovery SLOs under load
            try:
                reelect_s = await self.cluster.wait_all_leaders(
                    timeout=sc.slos["convergence_s"])
            except TimeoutError as e:
                res.slos["reelect_s"] = None
                raise AssertionError(
                    f"[seed {sc.seed}] re-election convergence SLO "
                    f"missed ({sc.slos['convergence_s']}s): {e}") from None
            res.slos["reelect_s"] = round(reelect_s, 3)
            res.slos["convergence_bound_s"] = sc.slos["convergence_s"]
            # keep load flowing through a fixed post-convergence window:
            # the recovery-throughput fraction compares it to the
            # pre-fault baseline (writers mid-retry at heal time need a
            # couple of client timeouts to drain back to steady state)
            t_rec = time.monotonic()
            window = float(sc.config.get("recovery_window_s", 2.0))
            await asyncio.sleep(window)
            t_stop = time.monotonic()
            await writers.stop()
            try:
                await self.cluster.wait_quiesced(
                    timeout=sc.slos["recovery_s"])
            except TimeoutError as e:
                raise AssertionError(
                    f"[seed {sc.seed}] catch-up SLO missed "
                    f"({sc.slos['recovery_s']}s): {e}") from None
            res.baseline_cps = writers.rate_in(self._t0, t_fault)
            res.recovery_cps = writers.rate_in(t_rec, t_stop)
            res.acked = writers.total_acked
            res.attempts = writers.attempts

            # Recovery pairing BEFORE the invariant checks: by this point
            # the faults healed and the recovery SLOs (convergence +
            # catch-up) were observed, so a run that then fails a DATA
            # invariant still journals its fault-recovered pairs — the
            # flight recorder attached to the failure artifact must show
            # the fault window closed, not dangling.
            for rec in [r for r in res.journal
                        if r["kind"] == KIND_INJECTED_FAULT]:
                self._journal(KIND_FAULT_RECOVERED, None,
                              f"recovered: {rec['detail']} "
                              f"(reelect {res.slos['reelect_s']}s)",
                              fault_id=rec["fault"])
            # ------------------------------------------------ invariants
            await self._settle_replicas()
            self._verify(writers)
            res.passed = True
        except Exception as e:  # CancelledError (BaseException) propagates
            res.error = f"{type(e).__name__}: {e}"
        finally:
            link_faults().heal_all()
            self._disarm_injections()
            self._disarm_grey()
            await self._disarm_rebalance()
            await writers.stop()
            for victim in list(self._killed):
                self._killed.remove(victim)
                try:
                    await self.cluster.restart(victim)
                except Exception:
                    LOG.exception("post-scenario restart of %s failed",
                                  victim)
        return res

    async def _settle_replicas(self, timeout: float = 10.0) -> None:
        """Writers are stopped and faults healed, but wait_quiesced samples
        the leader's commit once — a commit landing after its settled pass
        leaves a follower's apply a few entries behind at snapshot time.
        That gap is in-flight apply work, not divergence: wait it out
        bounded (a true divergence never closes, so _verify still fires)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            if all(len({d.applied_index
                        for d in self.cluster.divisions(g.group_id)}) <= 1
                   for g in self.cluster.groups):
                return
            await asyncio.sleep(0.05)

    def _shed_now(self) -> int:
        return sum(s.serving.admission.shed_total
                   for s in self.cluster.servers.values()
                   if getattr(s, "serving", None) is not None)

    def _verify(self, writers: _Writers) -> None:
        sc, res = self.scenario, self.result
        seed = sc.seed
        if writers.mode == "counter":
            # counter oracle at the many-group shape: per group,
            # acked <= counter <= attempts (zero lost acks; retry-cache
            # dedupe bounds above), all replicas agree
            lost, diverged = 0, 0
            for gid, acked in writers.acked_per_group.items():
                deltas = [d.state_machine.counter
                          - writers.counter_base.get(
                              (gid, str(d.member_id.peer_id)), 0)
                          for d in self.cluster.divisions(gid)]
                if len(set(deltas)) > 1:
                    diverged += 1
                if min(deltas, default=0) < acked:
                    lost += 1
                if max(deltas, default=0) > \
                        writers.attempts_per_group.get(gid, 0):
                    res.checks.setdefault("over_applied_groups", 0)
                    res.checks["over_applied_groups"] += 1
            res.checks.update({"lost_ack_groups": lost,
                               "diverged_groups": diverged,
                               "groups_checked":
                                   len(writers.acked_per_group)})
            assert diverged == 0, \
                f"[seed {seed}] {diverged} group(s) diverged across replicas"
            assert lost == 0, \
                f"[seed {seed}] {lost} group(s) lost acked writes"
            assert not res.checks.get("over_applied_groups"), \
                (f"[seed {seed}] duplicate applies on "
                 f"{res.checks['over_applied_groups']} group(s)")
        else:
            seqs = {str(d.member_id.peer_id): list(d.state_machine.applied)
                    for d in self.cluster.divisions()}
            first = next(iter(seqs.values()), [])
            for member, seq in seqs.items():
                assert seq == first, \
                    (f"[seed {seed}] replica divergence at {member}: "
                     f"{len(seq)} vs {len(first)} applied")
            # dedupe/loss oracle over THIS run's tagged payloads only —
            # a long-lived campaign cluster accumulates every scenario's
            # history in the recording SMs
            tag = writers.tag.encode()
            counts: dict = {}
            for p in first:
                if p.startswith(tag):
                    counts[p] = counts.get(p, 0) + 1
            dupes = {p: c for p, c in counts.items() if c > 1}
            assert not dupes, \
                f"[seed {seed}] duplicated applies: {dict(list(dupes.items())[:5])}"
            missing = [p for p in writers.acked if counts.get(p, 0) != 1]
            assert not missing, \
                (f"[seed {seed}] lost acked writes "
                 f"({len(missing)}): {missing[:10]}")
            res.checks.update({"applied": sum(counts.values()),
                               "acked": len(writers.acked),
                               "dupes": 0, "lost": 0})
        min_acked = int(sc.config.get("min_acked", 10))
        assert res.acked >= min_acked, \
            (f"[seed {seed}] scenario acked only {res.acked} writes "
             f"(< {min_acked}): load never got through")
        # Overload SLO (serving plane): shedding must have actually
        # happened (the budget was crossed), every shed attempt must
        # have surfaced as a TYPED overload reply — a client timeout is
        # a silent drop, exactly what bounded pending exists to prevent.
        shed_total = self._shed_now() - getattr(self, "_shed_base", 0)
        res.checks["shed_total"] = shed_total
        res.checks["client_timeouts"] = writers.timeouts
        res.checks["shed_surfaced"] = writers.shed_surfaced
        if sc.config.get("expect_shed"):
            assert shed_total > 0, \
                (f"[seed {seed}] overload scenario never crossed the "
                 f"pending budget: nothing was shed")
            assert writers.timeouts == 0, \
                (f"[seed {seed}] {writers.timeouts} client timeout(s) "
                 f"under overload: shed requests must get typed replies, "
                 f"not silent drops")
        if sc.config.get("expect_grey"):
            self._verify_grey()
        if sc.config.get("expect_rebalance"):
            self._verify_rebalance()

    # ------------------------------------------------- grey-follower SLO

    def _arm_grey(self) -> None:
        """Retune the lag ledger + grey detector for the scenario's write
        rates (a latency fault of a few hundred ms puts a follower a
        handful of entries behind, not the production default of 64) and
        capture per-server event baselines; restored in _disarm_grey."""
        cfg = self.scenario.config
        if not cfg.get("expect_grey"):
            return
        self._grey_saved: dict = {}
        self._grey_base: dict = {}
        for name, srv in self.cluster.servers.items():
            wd = srv.watchdog
            if wd is None:
                continue
            led = srv.engine.ledger
            self._grey_saved[name] = (
                led.lag_threshold, led.up_window_ms, wd.grey_fraction,
                wd.grey_min_groups, wd.grey_rounds)
            led.lag_threshold = int(cfg.get("grey_lag_entries", 2))
            led.up_window_ms = int(cfg.get("grey_up_window_ms", 8000))
            wd.grey_fraction = float(cfg.get("grey_fraction", 0.5))
            wd.grey_min_groups = int(cfg.get("grey_min_groups", 2))
            wd.grey_rounds = int(cfg.get("grey_rounds", 1))
            self._grey_base[name] = wd.last_seq

    def _disarm_grey(self) -> None:
        for name, saved in getattr(self, "_grey_saved", {}).items():
            srv = self.cluster.servers.get(name)
            if srv is None or srv.watchdog is None:
                continue
            led = srv.engine.ledger
            (led.lag_threshold, led.up_window_ms,
             srv.watchdog.grey_fraction, srv.watchdog.grey_min_groups,
             srv.watchdog.grey_rounds) = saved
        self._grey_saved = {}

    def _verify_grey(self) -> None:
        """The grey SLO: at least one grey-follower event during the
        fault window, every one paired with a grey-recovered close.  A
        forced watchdog pass per server first — writers are stopped and
        links healed, so the pass deterministically closes any episode
        still open instead of racing the background cadence."""
        from ratis_tpu.server.watchdog import (KIND_GREY_FOLLOWER,
                                               KIND_GREY_RECOVERED)
        seed = self.scenario.seed
        grey, recovered = [], []
        for name, srv in self.cluster.servers.items():
            wd = srv.watchdog
            if wd is None:
                continue
            try:
                wd.sample()
            except Exception:
                LOG.exception("forced watchdog pass on %s failed", name)
            base = self._grey_base.get(name, -1)
            for e in wd.events(since=base):
                if e["kind"] == KIND_GREY_FOLLOWER:
                    grey.append(e)
                elif e["kind"] == KIND_GREY_RECOVERED:
                    recovered.append(e)
        self.result.checks["grey_events"] = len(grey)
        self.result.checks["grey_recovered"] = len(recovered)
        assert grey, \
            (f"[seed {seed}] grey scenario raised no grey-follower "
             f"event: the ledger detector missed a slow-but-alive peer")
        rec_ids = {e.get("fault") for e in recovered}
        unpaired = [e for e in grey if e.get("fault") not in rec_ids]
        assert not unpaired, \
            (f"[seed {seed}] {len(unpaired)} grey episode(s) never "
             f"closed: {[e['fault'] for e in unpaired]}")


    # ---------------------------------------------- rebalance-storm SLO

    def _arm_rebalance(self) -> None:
        """Start a PlacementController on every server (armed thresholds:
        short interval, zero hysteresis, a near-zero hot-share floor) and
        retune the lag ledger so the scenario's slow follower actually
        scores low — the storm asserts the controller keeps actuating,
        and pairing every actuation, WHILE the faults are live.  Torn
        down in _disarm_rebalance (called at heal and again in the
        finally, idempotently)."""
        cfg = self.scenario.config
        if not cfg.get("expect_rebalance"):
            return
        from ratis_tpu.placement import PlacementController
        self._rebalance_ctrls: dict = {}
        self._rebalance_saved: dict = {}
        self._rebalance_base: dict = {}
        for name, srv in self.cluster.servers.items():
            wd = srv.watchdog
            if wd is None:
                continue
            led = srv.engine.ledger
            self._rebalance_saved[name] = (led.lag_threshold,
                                           led.up_window_ms)
            led.lag_threshold = int(cfg.get("rebalance_lag_entries", 2))
            led.up_window_ms = int(cfg.get("rebalance_up_window_ms", 8000))
            self._rebalance_base[name] = wd.last_seq
            ctrl = PlacementController(
                srv,
                interval_s=float(cfg.get("rebalance_interval_s", 0.3)),
                cooldown_s=float(cfg.get("rebalance_cooldown_s", 1.0)),
                max_per_round=int(cfg.get("rebalance_max_per_round", 2)),
                hot_share=float(cfg.get("rebalance_hot_share", 0.01)),
                hysteresis=0.0, steer_ttl_s=2.0, transfer_timeout_s=2.0)
            ctrl.start()
            srv.placement = ctrl
            self._rebalance_ctrls[name] = ctrl

    async def _disarm_rebalance(self) -> None:
        """Close every storm controller (idempotent: a killed server's
        close() already shut its controller down; re-closing is a no-op)
        and restore the retuned ledger thresholds on surviving servers."""
        for name, ctrl in list(getattr(self,
                                       "_rebalance_ctrls", {}).items()):
            try:
                await ctrl.close()
            except Exception:
                LOG.exception("closing storm controller on %s failed",
                              name)
            srv = self.cluster.servers.get(name)
            if srv is not None and srv.placement is ctrl:
                srv.placement = None
        self._rebalance_ctrls = {}
        for name, saved in getattr(self,
                                   "_rebalance_saved", {}).items():
            srv = self.cluster.servers.get(name)
            if srv is None:
                continue
            (srv.engine.ledger.lag_threshold,
             srv.engine.ledger.up_window_ms) = saved
        self._rebalance_saved = {}

    def _verify_rebalance(self) -> None:
        """The storm SLO: the controller actuated at least once during
        the fault window, and EVERY rebalance event has its
        rebalance-done pair (a dangling actuation means the actuator
        dropped an outcome on the floor).  Journals live on the servers
        that emitted them: a killed server's journal died with it, so
        pairing is asserted per surviving journal — both halves of a
        pair always land in the same ring."""
        from ratis_tpu.server.watchdog import (KIND_REBALANCE,
                                               KIND_REBALANCE_DONE)
        seed = self.scenario.seed
        opened, closed = [], []
        for name, srv in self.cluster.servers.items():
            wd = srv.watchdog
            if wd is None:
                continue
            base = self._rebalance_base.get(name, -1)
            for e in wd.events(since=base):
                if e["kind"] == KIND_REBALANCE:
                    opened.append(e)
                elif e["kind"] == KIND_REBALANCE_DONE:
                    closed.append(e)
        self.result.checks["rebalance_events"] = len(opened)
        self.result.checks["rebalance_done"] = len(closed)
        assert opened, \
            (f"[seed {seed}] rebalance storm drove no actuations: the "
             f"controller never steered or transferred under the faults")
        done_ids = {e.get("fault") for e in closed}
        unpaired = [e for e in opened if e.get("fault") not in done_ids]
        assert not unpaired, \
            (f"[seed {seed}] {len(unpaired)} rebalance actuation(s) "
             f"never converged: {[e['fault'] for e in unpaired]}")


async def run_scenario(cluster, scenario: Scenario,
                       artifact_dir: Optional[str] = None) -> ScenarioResult:
    """Run one scenario on ``cluster``; on failure, write the replay
    artifact (``artifact_dir`` falls back to the cluster's
    ``raft.tpu.chaos.artifact-dir``)."""
    runner = ScenarioRunner(cluster, scenario)
    result = await runner.run()
    if not result.passed:
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        snap = getattr(cluster, "flight_snapshots", None)
        if snap is not None:
            # the telemetry window across the fault rides in the replay
            # artifact: rates/occupancy/hot-groups + the paired
            # injected-fault journal, not just the end state
            result.flight = snap(
                f"chaos-{scenario.name}-seed{scenario.seed}")
        d = artifact_dir or RaftServerConfigKeys.Chaos.artifact_dir(
            cluster.properties)
        if d:
            path = write_artifact(result, scenario, d)
            LOG.warning("chaos scenario %s (seed %s) FAILED: %s — replay "
                        "artifact at %s", scenario.name, scenario.seed,
                        result.error, path)
    return result
