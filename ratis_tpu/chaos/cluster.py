"""In-process chaos cluster harness: kill/restart, tail truncation, and
multi-group bring-up — the cluster the scenario engine drives.

Shape parity with the test MiniCluster (itself the reference
MiniRaftCluster analog, ratis-server/src/test/.../impl/MiniRaftCluster.java:86)
but packaged INSIDE ``ratis_tpu`` so the replay tool and the bench
campaign can build one without importing the test tree, and extended
with the pieces chaos needs: multi-group hosting at the batched shape
(appointed-leader wave bring-up, like tools/bench_cluster), durable
storage with crash-time tail truncation, and ``raft.tpu.chaos.enabled``
armed so every transport consults the link-fault table.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import List, Optional

from ratis_tpu.chaos.faults import (find_group_current_dirs,
                                    find_shared_shard_dirs,
                                    truncate_log_tail,
                                    truncate_shared_log_tail)
from ratis_tpu.chaos.link import link_faults
from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
from ratis_tpu.models.counter import CounterStateMachine
from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                           NotLeaderException, RaftException)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.protocol.requests import RaftClientRequest, write_request_type
from ratis_tpu.server.division import Division
from ratis_tpu.server.server import RaftServer
from ratis_tpu.server.statemachine import (BaseStateMachine,
                                           TransactionContext)
from ratis_tpu.transport.simulated import (SimulatedNetwork,
                                           SimulatedTransportFactory)

LOG = logging.getLogger(__name__)

DEFAULT_TIMEOUT = 15.0

_handed_out_ports: set[int] = set()


def _free_port() -> int:
    """Bind-then-close port allocation that never hands the same port out
    twice in this process (same race fix as the test MiniCluster)."""
    import socket
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port not in _handed_out_ports:
            _handed_out_ports.add(port)
            return port


class ChaosRecordingStateMachine(BaseStateMachine):
    """Records every applied payload in order — the exactly-once /
    replica-agreement oracle for small scenario clusters (the reference's
    SimpleStateMachine4Testing role)."""

    def __init__(self) -> None:
        super().__init__()
        self.applied: List[bytes] = []

    async def start_transaction(self, request) -> TransactionContext:
        return TransactionContext(client_request=request,
                                  log_data=request.message.content)

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        e = trx.log_entry
        payload = (e.smlog.log_data if e is not None and e.smlog is not None
                   else (trx.log_data or b""))
        self.applied.append(payload)
        if e is not None:
            self.update_last_applied_term_index(e.term, e.index)
        return Message.value_of(str(len(self.applied)))

    async def query(self, request: Message) -> Message:
        return Message.value_of(str(len(self.applied)))

    async def query_stale(self, request: Message, min_index: int) -> Message:
        return await self.query(request)


def chaos_properties(num_groups: int = 1, batched: Optional[bool] = None,
                     seed: int = 0) -> RaftProperties:
    """Chaos-armed cluster properties.  Small clusters get the fast
    election timeouts the test MiniCluster uses; the 1024-group batched
    shape reuses the bench's density-scaled cost model so the campaign
    stresses exactly the configuration the perf rungs measure."""
    if num_groups >= 64 or batched:
        from ratis_tpu.tools.bench_cluster import bench_properties
        p = bench_properties(batched=True if batched is None else batched,
                             num_groups=num_groups)
    else:
        p = RaftProperties()
        RaftServerConfigKeys.Rpc.set_timeout(p, "100ms", "200ms")
        p.set("raft.tpu.engine.tick-interval", "5ms")
        RaftServerConfigKeys.Log.set_use_memory(p, True)
    p.set(RaftServerConfigKeys.Chaos.ENABLED_KEY, "true")
    p.set(RaftServerConfigKeys.Chaos.SEED_KEY, str(seed))
    return p


class ChaosCluster:
    """``num_servers`` in-process peers hosting ``num_groups`` sibling
    groups, with crash/restart (plus durable tail truncation) and the
    chaos link-fault plane armed on every transport."""

    def __init__(self, num_servers: int = 3, num_groups: int = 1,
                 properties: Optional[RaftProperties] = None,
                 transport: str = "sim", sm: str = "recording",
                 storage_root: Optional[str] = None, seed: int = 0):
        self.num_servers = num_servers
        self.num_groups = num_groups
        self.transport = transport
        self.seed = seed
        self.properties = (properties if properties is not None
                           else chaos_properties(num_groups, seed=seed))
        self.properties = self.properties.clone()
        self.properties.set(RaftServerConfigKeys.Chaos.ENABLED_KEY, "true")
        # Continuous telemetry ON for chaos clusters (unless the caller
        # pinned it): a failing scenario attaches every server's flight
        # recorder window to its replay artifact, so the campaign's
        # post-mortem carries the rate history across the fault, not just
        # the final snapshot.  Fast cadence — scenarios last seconds.
        tk = RaftServerConfigKeys.Telemetry
        if self.properties.get(tk.ENABLED_KEY) is None:
            self.properties.set(tk.ENABLED_KEY, "true")
        if self.properties.get(tk.INTERVAL_KEY) is None:
            self.properties.set(tk.INTERVAL_KEY, "200ms")
        self.storage_root = storage_root
        if storage_root is not None:
            RaftServerConfigKeys.Log.set_use_memory(self.properties, False)
            RaftServerConfigKeys.set_storage_dir(self.properties,
                                                 str(storage_root))
        if transport in ("tcp", "grpc"):
            from ratis_tpu.transport.base import TransportFactory
            import ratis_tpu.transport.grpc  # noqa: F401 (registers GRPC)
            import ratis_tpu.transport.tcp  # noqa: F401 (registers TCP)
            self.network = None
            self.factory = TransportFactory.get(
                "GRPC" if transport == "grpc" else "TCP")
            addr = lambda i: f"127.0.0.1:{_free_port()}"
        elif transport == "sim":
            self.network = SimulatedNetwork()
            self.factory = SimulatedTransportFactory(self.network)
            addr = lambda i: f"sim:s{i}"
            # density-scaled rpc deadline, like BenchCluster: a
            # legitimately-busy handler at thousands of co-hosted groups
            # must not blow the sim's small-cluster 3s default
            self.network.request_timeout_s = max(
                3.0, RaftServerConfigKeys.Rpc.timeout_min(
                    self.properties).seconds)
        else:
            raise ValueError(f"unknown chaos transport {transport!r}")
        self.peers = [RaftPeer(RaftPeerId.value_of(f"s{i}"), address=addr(i))
                      for i in range(num_servers)]
        self.groups = [RaftGroup.value_of(RaftGroupId.random_id(), self.peers)
                       for _ in range(num_groups)]
        if sm == "counter":
            self._sm_factory = CounterStateMachine
        else:
            self._sm_factory = ChaosRecordingStateMachine
        self.servers: dict[RaftPeerId, RaftServer] = {}
        self._dead: dict[RaftPeerId, RaftPeer] = {}
        self._call_ids = itertools.count(1)
        self._leader_hint: dict[RaftGroupId, RaftPeerId] = {}
        link_faults().reseed(seed)

    # ---------------------------------------------------------- lifecycle

    def _new_server(self, peer: RaftPeer) -> RaftServer:
        return RaftServer(
            peer.id, peer.address,
            state_machine_registry=lambda gid: self._sm_factory(),
            properties=self.properties, transport_factory=self.factory,
            group=self.groups[0])

    async def start(self, appoint: bool = True,
                    leader_timeout: float = 60.0) -> None:
        for peer in self.peers:
            self.servers[peer.id] = self._new_server(peer)
        await asyncio.gather(*(s.start() for s in self.servers.values()))
        first = self.peers[0].id
        wave = 128
        for i in range(1, len(self.groups), wave):
            batch = self.groups[i:i + wave]
            await asyncio.gather(*(s.group_add(g) for g in batch
                                   for s in self.servers.values()))
            if appoint:
                await self._appoint(batch, first)
        if appoint:
            await self._appoint(self.groups[:1], first)
        await self.wait_all_leaders(timeout=leader_timeout)

    async def _appoint(self, groups: list[RaftGroup],
                       server_id: RaftPeerId) -> None:
        """Appointed-leader bootstrap (deployment-mode bring-up; elections
        remain the fallback for any group the bootstrap cannot claim)."""
        server = self.servers[server_id]
        boots = []
        for g in groups:
            d = server.divisions.get(g.group_id)
            if d is not None and d.is_follower():
                boots.append(server.bootstrap_division(g.group_id))
        if boots:
            await asyncio.gather(*boots, return_exceptions=True)

    async def close(self) -> None:
        link_faults().heal_all()
        if self.network is not None:
            self.network.unblock_all()
        await asyncio.gather(*(s.close() for s in self.servers.values()),
                             return_exceptions=True)
        self.servers.clear()

    # ------------------------------------------------------- fault plane

    async def kill(self, peer_id: RaftPeerId) -> None:
        """Crash one server (close is the sharpest crash an in-process
        harness can deliver; in-flight RPCs toward it start failing)."""
        server = self.servers.pop(peer_id)
        self._dead[peer_id] = next(p for p in self.peers if p.id == peer_id)
        await server.close()

    async def restart(self, peer_id: RaftPeerId,
                      truncate_tail: int = 0) -> RaftServer:
        """Restart a killed server; with durable storage,
        ``truncate_tail`` first drops that many entries off every hosted
        group's log tail on disk (the lost-write-back-cache crash)."""
        peer = self._dead.pop(peer_id, None) \
            or next(p for p in self.peers if p.id == peer_id)
        if truncate_tail and self.storage_root is not None:
            root = f"{self.storage_root}/{peer_id}"
            for current in find_group_current_dirs(root):
                truncate_log_tail(current, truncate_tail)
            # shared log plane (raft.tpu.log.shared): the tail lives in
            # the per-shard interleaved segments, one chop per shard
            for shard in find_shared_shard_dirs(root):
                truncate_shared_log_tail(shard, truncate_tail)
        server = self._new_server(peer)
        self.servers[peer_id] = server
        await server.start()
        # memory-log multi-group restarts have nothing on disk to
        # boot-scan: re-add the hosted groups (empty logs; the leaders
        # re-replicate everything — the volatile-restart recovery shape)
        for g in self.groups:
            if g.group_id not in server.divisions:
                await server.group_add(g)
        return server

    def emit_fault_event(self, kind: str, detail: str,
                         fault_id: str) -> None:
        """Journal one fault event through every live server's watchdog —
        the /events plane is the campaign's flight recorder."""
        for s in self.servers.values():
            if s.watchdog is not None:
                s.watchdog.emit(kind, None, detail, fault=fault_id)

    def flight_snapshots(self, reason: str) -> list[dict]:
        """Every live server's flight-recorder window (telemetry-enabled
        servers only) — the scenario runner attaches these to a failing
        run's replay artifact."""
        out = []
        for s in self.servers.values():
            if s.flight is not None:
                try:
                    out.append(s.flight.snapshot(reason))
                except Exception:
                    LOG.exception("flight snapshot of %s failed", s.peer_id)
        return out

    # ------------------------------------------------------------ queries

    def live_peer_ids(self) -> list[RaftPeerId]:
        return sorted(self.servers, key=str)

    def all_peer_ids(self) -> list[RaftPeerId]:
        return [p.id for p in self.peers]

    def divisions(self, gid: Optional[RaftGroupId] = None) -> list[Division]:
        gid = gid or self.groups[0].group_id
        return [s.divisions[gid] for s in self.servers.values()
                if gid in s.divisions]

    def leaders(self, gid: Optional[RaftGroupId] = None) -> list[Division]:
        return [d for d in self.divisions(gid) if d.is_leader()]

    async def wait_for_leader(self, gid: Optional[RaftGroupId] = None,
                              timeout: float = DEFAULT_TIMEOUT) -> Division:
        """One leader at the top term, with no rival at that term."""
        gid = gid or self.groups[0].group_id
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            leaders = self.leaders(gid)
            if leaders:
                top = max(leaders, key=lambda d: d.state.current_term)
                if all(d.state.current_term < top.state.current_term
                       for d in leaders if d is not top):
                    self._leader_hint[gid] = top.member_id.peer_id
                    return top
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"no leader for {gid} after {timeout}s; roles: "
            f"{[(str(d.member_id.peer_id), d.role.name, d.state.current_term) for d in self.divisions(gid)]}")

    async def wait_all_leaders(self, timeout: float = 60.0,
                               groups: Optional[list] = None) -> float:
        """Every group has a READY leader (startup entry committed);
        returns how long convergence took — the re-election SLO number."""
        t0 = time.monotonic()
        pending = {g.group_id for g in (groups or self.groups)}
        deadline = t0 + timeout
        while pending and time.monotonic() < deadline:
            done = set()
            for gid in pending:
                for s in self.servers.values():
                    d = s.divisions.get(gid)
                    if d is not None and d.is_leader() \
                            and d.leader_ctx is not None \
                            and d.leader_ctx.leader_ready.done():
                        self._leader_hint[gid] = d.member_id.peer_id
                        done.add(gid)
                        break
            pending -= done
            if pending:
                await asyncio.sleep(0.05)
        if pending:
            raise TimeoutError(
                f"{len(pending)}/{len(groups or self.groups)} groups have "
                f"no ready leader after {timeout}s")
        return time.monotonic() - t0

    async def wait_quiesced(self, timeout: float = 60.0,
                            groups: Optional[list] = None) -> None:
        """Replication + apply drained: on every group, each live replica
        applied up to the leader's committed index."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        gids = [g.group_id for g in (groups or self.groups)]
        while loop.time() < deadline:
            settled = True
            for gid in gids:
                divs = self.divisions(gid)
                leaders = [d for d in divs if d.is_leader()]
                if not leaders:
                    settled = False
                    break
                commit = max(int(d.state.log.get_last_committed_index())
                             for d in leaders)
                if any(d.applied_index < commit for d in divs):
                    settled = False
                    break
            if settled:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"cluster did not quiesce within {timeout}s")

    # ------------------------------------------------------------- client

    def new_client(self, group: Optional[RaftGroup] = None,
                   retry_policy=None):
        """A full RaftClient (retry + failover + retry-cache-correct call
        ids) bound to one group — the writer the invariants trust."""
        from ratis_tpu.client import RaftClient
        return (RaftClient.builder()
                .set_raft_group(group or self.groups[0])
                .set_transport(
                    self.factory.new_client_transport(self.properties))
                .set_retry_policy(retry_policy)
                .set_properties(self.properties)
                .build())

    async def write(self, gid: RaftGroupId, message: bytes = b"INCREMENT",
                    client=None, client_id: Optional[ClientId] = None,
                    timeout: float = 30.0) -> bool:
        """One write with leader-hint failover on a raw client transport
        (the campaign's high-volume driver; a fixed (client_id, call_id)
        pair per payload keeps retries retry-cache-deduped)."""
        own = client is None
        if own:
            client = self.factory.new_client_transport(self.properties)
        client_id = client_id or ClientId.random_id()
        call_id = next(self._call_ids)
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        target = self._leader_hint.get(gid) or next(iter(self.servers), None)
        try:
            while loop.time() < deadline:
                server = self.servers.get(target) if target else None
                if server is None:
                    live = self.live_peer_ids()
                    if not live:
                        await asyncio.sleep(0.05)
                        continue
                    target = live[0]
                    continue
                req = RaftClientRequest(client_id, target, gid, call_id,
                                        Message.value_of(message),
                                        type=write_request_type(),
                                        timeout_ms=8000.0)
                try:
                    reply = await asyncio.wait_for(
                        client.send_request(server.address, req), 10.0)
                except (RaftException, asyncio.TimeoutError, OSError):
                    await asyncio.sleep(0.05)
                    live = self.live_peer_ids()
                    if live:
                        target = live[(live.index(target) + 1) % len(live)] \
                            if target in live else live[0]
                    continue
                if reply.success:
                    self._leader_hint[gid] = target
                    return True
                exc = reply.exception
                if isinstance(exc, NotLeaderException):
                    if exc.suggested_leader is not None:
                        target = exc.suggested_leader.id
                    else:
                        live = self.live_peer_ids()
                        target = live[(live.index(target) + 1) % len(live)] \
                            if target in live else (live[0] if live else None)
                    await asyncio.sleep(0.02)
                    continue
                if isinstance(exc, LeaderNotReadyException):
                    await asyncio.sleep(0.02)
                    continue
                return False
            return False
        finally:
            if own:
                try:
                    await client.close()
                except Exception:
                    pass
