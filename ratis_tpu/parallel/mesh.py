"""Mesh sharding of the quorum engine: the multi-chip scaling axis.

The framework's parallelism axis is the *multi-raft group batch* — the
analog of the reference's one-process-many-RaftGroups multiplexing
(RaftServerProxy.ImplMap, RaftServerProxy.java:89): thousands of
independent groups, so the `[G, ...]` state arrays shard cleanly over a
device mesh with NO cross-device collectives in the hot kernel (each
group's quorum math is row-local; XLA's SPMD partitioner keeps the whole
``engine_step`` collective-free, so scaling is embarrassingly linear over
ICI).  Host-side ack events travel one of two ways: the legacy path
replicates them to all devices (the scatter by group id resolves locally
on the device that owns the row), while the production fast tick routes
each event to the owning slice's [7, S, E] plane
(:func:`sliced_event_sharding`) so a device only ever scans the E/S
columns that target rows it holds.

These helpers build the mesh, the in/out shardings for
:func:`ratis_tpu.ops.quorum.engine_step`, and a jitted sharded step —
used by the driver's ``dryrun_multichip``, the benchmark, and any
multi-chip deployment.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

GROUP_AXIS = "groups"


def make_group_mesh(n_devices: Optional[int] = None, devices=None):
    """A 1-D mesh over the group axis (jax.sharding.Mesh)."""
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)}")
    return Mesh(np.array(devices), axis_names=(GROUP_AXIS,))


def engine_shardings(mesh):
    """(in_shardings tuple, out_shardings EngineStep) for engine_step:
    group-major arrays shard over the mesh, packed ack events and scalars
    replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.quorum import EngineStep
    grp = NamedSharding(mesh, P(GROUP_AXIS))            # [G]
    grp_peer = NamedSharding(mesh, P(GROUP_AXIS, None))  # [G, P]
    repl = NamedSharding(mesh, P())                      # events / scalars
    in_shardings = (
        grp_peer,  # match_index
        grp_peer,  # last_ack_ms
        repl,      # ev_group
        repl,      # ev_peer
        repl,      # ev_match
        repl,      # ev_time_ms
        repl,      # ev_valid
        grp_peer,  # self_mask
        grp,       # flush_index
        grp_peer,  # conf_cur
        grp_peer,  # conf_old
        grp,       # commit_index
        grp,       # first_leader_index
        grp,       # role
        grp,       # election_deadline_ms
        repl,      # now_ms
        repl,      # leadership_timeout_ms
    )
    out_shardings = EngineStep(grp_peer, grp_peer, grp, grp, grp, grp)
    return in_shardings, out_shardings


def sharded_engine_step(mesh):
    """jit(engine_step) with the group axis sharded over ``mesh``."""
    import jax

    from ratis_tpu.ops.quorum import engine_step
    in_shardings, out_shardings = engine_shardings(mesh)
    return jax.jit(engine_step, in_shardings=in_shardings,
                   out_shardings=out_shardings)


def device_state_shardings(mesh):
    """Sharding for ops.quorum.DeviceState: every [G,...] array shards its
    group axis over the mesh (row-local quorum math means the partitioner
    keeps the resident step collective-free)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.quorum import DeviceState
    grp = NamedSharding(mesh, P(GROUP_AXIS))
    grp_peer = NamedSharding(mesh, P(GROUP_AXIS, None))
    return DeviceState(
        match_index=grp_peer, last_ack_ms=grp_peer, self_mask=grp_peer,
        conf_cur=grp_peer, conf_old=grp_peer, role=grp,
        flush_index=grp, commit_index=grp, first_leader_index=grp,
        election_deadline_ms=grp)


def sharded_resident_fast_step(mesh):
    """jit(engine_step_resident_fast) with the DeviceState sharded over the
    group axis, donated (the PRODUCTION steady-state tick, not the
    stateless engine_step toy): packed events + meta replicate; the [4, G]
    packed output shards its group axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.quorum import ResidentFastStep, engine_step_resident_fast
    repl = NamedSharding(mesh, P())
    out_grp = NamedSharding(mesh, P(None, GROUP_AXIS))
    return jax.jit(
        engine_step_resident_fast,
        in_shardings=(device_state_shardings(mesh), repl, repl),
        out_shardings=ResidentFastStep(device_state_shardings(mesh),
                                       out_grp),
        donate_argnums=(0,))


def sliced_event_sharding(mesh):
    """Sharding for the [7, S, E] pre-routed event planes of
    :func:`ratis_tpu.ops.quorum.engine_step_resident_fast_sliced`: the
    slice axis maps onto the group axis of the mesh, so each device
    receives ONLY its own slice's packed events."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(None, GROUP_AXIS, None))


def sharded_resident_fast_step_sliced(mesh):
    """jit(engine_step_resident_fast_sliced) over ``mesh``: DeviceState
    sharded + donated as in :func:`sharded_resident_fast_step`, but events
    arrive slice-routed ([7, S, E], slice axis sharded) instead of
    replicated — the production mesh tick.  Each device scatters only the
    E/S event columns that target rows it owns; the partitioner keeps the
    whole step collective-free."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.quorum import (ResidentFastStep,
                                      engine_step_resident_fast_sliced)
    repl = NamedSharding(mesh, P())
    out_grp = NamedSharding(mesh, P(None, GROUP_AXIS))
    return jax.jit(
        engine_step_resident_fast_sliced,
        in_shardings=(device_state_shardings(mesh),
                      sliced_event_sharding(mesh), repl),
        out_shardings=ResidentFastStep(device_state_shardings(mesh),
                                       out_grp),
        donate_argnums=(0,))


def pad_to_mesh(groups: int, n_devices: int) -> int:
    """Round a group capacity up to the next multiple of the mesh size.
    Padded rows stay ROLE_UNUSED (masked invalid) and cost nothing; this
    replaces the old hard requirement that ``mesh-devices`` divide
    ``max-groups``."""
    n = max(1, int(n_devices))
    return -(-int(groups) // n) * n


def sharded_resident_step(mesh):
    """jit(engine_step_resident): the dirty-row refresh variant of the
    resident tick, DeviceState sharded + donated; refresh rows and packed
    events replicate (the scatter by row index resolves locally)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.quorum import (DeviceState, ResidentStep,
                                      engine_step_resident)
    repl = NamedSharding(mesh, P())
    grp = NamedSharding(mesh, P(GROUP_AXIS))
    state_sh = device_state_shardings(mesh)
    # state + 18 replicated inputs (11 refresh-row arrays, 5 packed
    # event arrays, now_ms, leadership_timeout_ms)
    in_shardings = (state_sh,) + (repl,) * 18
    out_shardings = ResidentStep(state_sh, grp, grp, grp, grp)
    return jax.jit(engine_step_resident, in_shardings=in_shardings,
                   out_shardings=out_shardings, donate_argnums=(0,))


def sharded_ledger_pass(mesh, num_peers: int):
    """jit(ops.ledger.ledger_pass) with the group axis sharded over
    ``mesh``: the telemetry tick reads the same mesh-slice layout the
    resident engine keeps, so a mesh deployment's observability pass
    uploads each host-mirror slice to the device that owns it.  The
    packed output replicates — its per-peer sections are cross-group
    reductions, and collectives are fine OFF the hot path (integer sums
    and exact-f32 counts, so the result is bit-identical to the
    single-device pass; enforced in tests/test_lag_ledger.py)."""
    import functools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ratis_tpu.ops.ledger import ledger_pass
    grp = NamedSharding(mesh, P(GROUP_AXIS))
    grp_peer = NamedSharding(mesh, P(GROUP_AXIS, None))
    repl = NamedSharding(mesh, P())
    in_shardings = (
        grp,       # role
        grp_peer,  # match_index
        grp,       # commit_index
        grp,       # applied_index
        grp_peer,  # conf_cur
        grp_peer,  # conf_old
        grp_peer,  # self_mask
        grp_peer,  # last_ack_ms
        grp_peer,  # peer_index
        grp,       # prev_commit
        grp,       # prev_valid
        repl,      # now_ms
        repl,      # lag_threshold
        repl,      # up_window_ms
    )
    return jax.jit(functools.partial(ledger_pass, num_peers=num_peers),
                   in_shardings=in_shardings, out_shardings=repl)


def shard_device_state(mesh, state):
    """device_put a DeviceState with its group-axis shardings."""
    import jax
    sh = device_state_shardings(mesh)
    return type(state)(*(jax.device_put(a, s)
                         for a, s in zip(state, sh)))


def shard_batch(mesh, args: Sequence):
    """device_put every engine_step arg with its proper sharding; the group
    axis size must be divisible by the mesh size."""
    import jax
    import jax.numpy as jnp
    in_shardings, _ = engine_shardings(mesh)
    g = np.shape(args[0])[0]
    n = mesh.devices.size
    if g % n != 0:
        raise ValueError(f"group count {g} not divisible by mesh size {n}")
    return [jax.device_put(jnp.asarray(a), s)
            for a, s in zip(args, in_shardings)]
