"""Multi-device scaling of the quorum engine (SURVEY.md §2.9: the
multi-raft group batch is this framework's data-parallel axis)."""

from ratis_tpu.parallel.mesh import (GROUP_AXIS, engine_shardings,
                                     make_group_mesh, shard_batch,
                                     sharded_engine_step)

__all__ = ["GROUP_AXIS", "engine_shardings", "make_group_mesh",
           "shard_batch", "sharded_engine_step"]
