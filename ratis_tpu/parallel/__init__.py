"""Multi-device scaling of the quorum engine (SURVEY.md §2.9: the
multi-raft group batch is this framework's data-parallel axis)."""

from ratis_tpu.parallel.mesh import (GROUP_AXIS, device_state_shardings,
                                     engine_shardings, make_group_mesh,
                                     shard_batch, shard_device_state,
                                     sharded_engine_step,
                                     sharded_resident_fast_step,
                                     sharded_resident_step)

__all__ = ["GROUP_AXIS", "device_state_shardings", "engine_shardings",
           "make_group_mesh", "shard_batch", "shard_device_state",
           "sharded_engine_step", "sharded_resident_fast_step",
           "sharded_resident_step"]
