"""The placement actuator: rate-limited execution of a PlacementPlan.

Transfers ride the EXISTING admin path — an in-process
TransferLeadership RaftClientRequest submitted on the group's owning
loop, exactly the frames the shell/client transfer sends — so every
guard on that path (leader check, hibernation wake, voting-member
validation, the match-then-StartLeaderElection handshake) applies to
controller-initiated moves too.  Steering writes the server's
ReadSteering table (server/read.py), which the batched readIndex sweep
consults.

Rate limiting and anti-ping-pong:
- the per-round transfer cap is applied in the PLAN (policy.plan), so
  the dry-run and the executed round agree;
- every transferred group enters a per-group ``cooldown`` window here;
  the controller feeds the live cooldown set back into the next plan's
  ``exclude``;
- steering renewals inside an active TTL are silent (one journal pair
  per episode, not one per policy round).

Every actuation is journaled through the watchdog as a KIND_REBALANCE
event paired with a KIND_REBALANCE_DONE close (same fault-correlation
id, outcome in the detail) — emitted in a finally-like discipline so
even a shutdown mid-transfer leaves a paired ``aborted`` close, never a
dangling actuation.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import Optional

LOG = logging.getLogger(__name__)


class PlacementActuator:
    """Executes plans against the local server (controller frontend
    only; the shell executes through a real admin client instead)."""

    def __init__(self, server, *, cooldown_s: float,
                 steer_ttl_s: float, transfer_timeout_s: float):
        from ratis_tpu.protocol.ids import ClientId
        self.server = server
        self.cooldown_s = cooldown_s
        self.steer_ttl_s = steer_ttl_s
        self.transfer_timeout_s = transfer_timeout_s
        self._cooldown: dict[str, float] = {}  # group -> monotonic expiry
        self._client_id = ClientId.random_id()
        self._call_ids = itertools.count(1)
        self._seq = 0
        self.transfers_ok = 0
        self.transfers_failed = 0
        self.steers = 0
        self.skipped = 0

    def cooldown_groups(self, now: Optional[float] = None) -> set:
        """Groups still inside their post-transfer cooldown (pruned);
        the controller passes this as the next plan's ``exclude``."""
        if now is None:
            now = time.monotonic()
        dead = [g for g, t in self._cooldown.items() if t <= now]
        for g in dead:
            del self._cooldown[g]
        return set(self._cooldown)

    # ------------------------------------------------------------ journal

    def _emit(self, kind: str, group: Optional[str], detail: str,
              fault: str) -> None:
        wd = self.server.watchdog
        if wd is not None:
            wd.emit(kind, group, detail, fault=fault)

    def _fault_id(self) -> str:
        self._seq += 1
        return f"rebalance-{self.server.peer_id}-{self._seq}"

    # ------------------------------------------------------------ execute

    async def execute(self, plan) -> dict:
        """Run one plan; returns the round's outcome counts.  Repins are
        advisory and never executed."""
        from ratis_tpu.server.watchdog import (KIND_REBALANCE,
                                               KIND_REBALANCE_DONE)
        out = {"transfers_ok": 0, "transfers_failed": 0, "steers": 0,
               "skipped": 0}
        steering = self.server.read_steering
        for a in plan.steers():
            if not steering.steer(a.away_from, self.steer_ttl_s):
                continue  # renewal inside an active episode
            fid = self._fault_id()
            self._emit(KIND_REBALANCE, None,
                       f"steer reads away from {a.away_from}: {a.reason}",
                       fid)
            # steering is a table write: it converges the moment it
            # lands, so the episode's done pair closes immediately
            self._emit(KIND_REBALANCE_DONE, None,
                       f"steering {a.away_from} active "
                       f"({self.steer_ttl_s:g}s ttl): success", fid)
            out["steers"] += 1
            self.steers += 1

        now = time.monotonic()
        cooling = self.cooldown_groups(now)
        for a in plan.transfers():
            if a.group in cooling:
                out["skipped"] += 1
                self.skipped += 1
                continue
            div = (self.server.divisions.get(a.gid)
                   if a.gid is not None else None)
            if div is None or not div.is_leader():
                # leadership moved (or the plan came from a stale/foreign
                # view) between scoring and actuation — not an error
                out["skipped"] += 1
                self.skipped += 1
                continue
            self._cooldown[a.group] = now + self.cooldown_s
            fid = self._fault_id()
            self._emit(KIND_REBALANCE, a.group,
                       f"transfer leadership -> {a.to_peer}: {a.reason}",
                       fid)
            outcome, err = "failed", ""
            try:
                reply = await self._transfer(div, a.to_peer)
                if reply is not None and reply.success:
                    outcome = "success"
                    out["transfers_ok"] += 1
                    self.transfers_ok += 1
                else:
                    exc = getattr(reply, "exception", None)
                    err = str(exc or "no reply")[:120]
                    out["transfers_failed"] += 1
                    self.transfers_failed += 1
            except asyncio.CancelledError:
                self._emit(KIND_REBALANCE_DONE, a.group,
                           f"transfer -> {a.to_peer}: aborted (shutdown)",
                           fid)
                raise
            except Exception as e:
                err = f"{type(e).__name__}: {e}"[:120]
                out["transfers_failed"] += 1
                self.transfers_failed += 1
            self._emit(KIND_REBALANCE_DONE, a.group,
                       f"transfer -> {a.to_peer}: {outcome}"
                       + (f" ({err})" if err else ""), fid)
        return out

    async def _transfer(self, div, target: str):
        """Submit the admin TransferLeadership request in-process on the
        division's owning loop (the same request the shell/client path
        builds — bench_cluster.run_churn_bench drives it over a real
        transport)."""
        from ratis_tpu.protocol.admin import TransferLeadershipArguments
        from ratis_tpu.protocol.message import Message
        from ratis_tpu.protocol.requests import (RaftClientRequest,
                                                 RequestType,
                                                 admin_request_type)
        timeout_ms = self.transfer_timeout_s * 1000.0
        args = TransferLeadershipArguments(str(target), timeout_ms)
        req = RaftClientRequest(
            self._client_id, self.server.peer_id, div.group_id,
            next(self._call_ids), Message(args.to_payload()),
            type=admin_request_type(RequestType.TRANSFER_LEADERSHIP),
            timeout_ms=timeout_ms + 2000.0)
        return await self.server._run_on_division_loop(
            div.group_id, div.submit_client_request(req))
