"""The in-server placement policy loop (``raft.tpu.placement.enabled``).

Opt-in and zero-cost when off: the server only constructs this when the
key is set, so the default request/read paths are bit-identical to a
build without the subsystem.  When on, one scoring pass per interval
over data the host ALREADY collects — the lag & health ledger sample
(one fused device pass), the hot-group sketch's top-k, the admission
controller's shed counter, the watchdog's grey set — O(servers + k)
python, never a divisions walk (tools/check_hot_loops.py enforces it).

The loop builds the same ServerView shape the shell builds from scraped
endpoints, runs the same PlacementPolicy, and hands the plan to the
PlacementActuator, which feeds its live cooldown set back into the next
plan's exclude — so ``shell rebalance --dry-run`` against this server
prints exactly the plan the loop is executing, with the same reasons.

Observability: the ``placement_plane`` registry (plansComputed,
transfersIssued{reason=...}, steeredReads, lastImbalance) and the
``GET /placement`` route serving the last computed plan, explained.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ratis_tpu.metrics.registry import (MetricRegistries, MetricRegistryInfo,
                                        labeled)
from ratis_tpu.placement.actuate import PlacementActuator
from ratis_tpu.placement.policy import (ClusterSnapshot, HotGroup,
                                        PlacementPolicy, view_from_payloads)

LOG = logging.getLogger(__name__)


class PlacementController:
    """One per server.  Constructor kwargs override the raft.tpu.placement.*
    properties (the StallWatchdog idiom — tests and the chaos harness
    retune without rebuilding RaftProperties)."""

    def __init__(self, server, interval_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 max_per_round: Optional[int] = None,
                 hot_share: Optional[float] = None,
                 grey_score: Optional[float] = None,
                 hysteresis: Optional[float] = None,
                 steer_ttl_s: Optional[float] = None,
                 transfer_timeout_s: Optional[float] = None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        keys = RaftServerConfigKeys.Placement
        p = server.properties
        self.server = server
        self.interval_s = (interval_s if interval_s is not None
                           else keys.interval(p).seconds)
        self.policy = PlacementPolicy(
            hot_share=(hot_share if hot_share is not None
                       else keys.hot_share(p)),
            grey_score=(grey_score if grey_score is not None
                        else keys.grey_score(p)),
            hysteresis=(hysteresis if hysteresis is not None
                        else keys.hysteresis(p)),
            max_transfers_per_round=(max_per_round
                                     if max_per_round is not None
                                     else keys.max_transfers(p)))
        self.actuator = PlacementActuator(
            server,
            cooldown_s=(cooldown_s if cooldown_s is not None
                        else keys.cooldown(p).seconds),
            steer_ttl_s=(steer_ttl_s if steer_ttl_s is not None
                         else keys.steer_ttl(p).seconds),
            transfer_timeout_s=(transfer_timeout_s
                                if transfer_timeout_s is not None
                                else keys.transfer_timeout(p).seconds))
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self.rounds = 0
        self.last_plan = None
        self.last_imbalance = 0.0
        self._last_shed: Optional[int] = None
        self._last_shed_t: Optional[float] = None
        info = MetricRegistryInfo(prefix=str(server.peer_id),
                                  application="ratis", component="server",
                                  name="placement_plane")
        self.registry = MetricRegistries.global_registries().create(info)
        self.plans_computed = self.registry.counter("plansComputed")
        self._transfer_counters: dict = {}
        self.registry.gauge("steeredReads",
                            lambda: server.read_steering.steered)
        self.registry.gauge("lastImbalance", lambda: self.last_imbalance)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(
            self._run(), name=f"placement-{self.server.peer_id}")

    async def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        MetricRegistries.global_registries().remove(self.registry.info)

    # ------------------------------------------------------------- the loop

    async def _run(self) -> None:
        while self._running:
            await asyncio.sleep(self.interval_s)
            try:
                await self.round()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the controller must never take the server down with it
                LOG.exception("%s placement round failed",
                              self.server.peer_id)

    async def round(self) -> None:
        """One sense -> plan -> actuate pass.  Public so tests and the
        chaos harness can force a round."""
        snapshot = ClusterSnapshot(views=(self._local_view(),))
        plan = self.policy.plan(snapshot,
                                exclude=self.actuator.cooldown_groups())
        self.rounds += 1
        self.plans_computed.inc()
        self.last_plan = plan
        self.last_imbalance = plan.imbalance
        for t in plan.transfers():
            c = self._transfer_counters.get(t.category)
            if c is None:
                c = self.registry.counter(
                    labeled("transfersIssued", reason=t.category))
                self._transfer_counters[t.category] = c
            c.inc()
        await self.actuator.execute(plan)

    def _local_view(self):
        """This server's ServerView from already-collected sensor state:
        the lag payload (one ledger pass), the sketch's top-k with gid
        objects for the actuator, the watchdog's live grey set, and the
        admission shed rate over the last round."""
        srv = self.server
        lag = srv.lag_info()
        grey = (set(srv.watchdog._grey)
                if srv.watchdog is not None else set())
        shed = (srv.serving.admission.shed_total
                if getattr(srv, "serving", None) is not None else 0)
        now = time.monotonic()
        rate = 0.0
        if self._last_shed is not None and self._last_shed_t is not None:
            rate = max(0, shed - self._last_shed) \
                / max(1e-9, now - self._last_shed_t)
        self._last_shed, self._last_shed_t = shed, now
        view = view_from_payloads(peer=str(srv.peer_id), lag=lag,
                                  grey=grey, shed_rate=rate)
        view.shed_total = shed
        view.divisions = len(srv.divisions)
        tel = srv.telemetry
        if tel is not None:
            tel.maybe_sample()
            total = max(1, tel.sketch.total)
            hot = []
            for e in tel.sketch.top(None):
                gid = e["key"]
                div = srv.divisions.get(gid)
                hot.append(HotGroup(
                    group=str(gid),
                    share=round(e["count"] / total, 4),
                    share_min=round(
                        max(0, e["count"] - e["err"]) / total, 4),
                    pending=e["aux"] or 0,
                    led=div is not None and div.is_leader(),
                    shard=srv.shard_of_group(gid), gid=gid))
            view.hot_groups = tuple(hot)
        return view

    # ------------------------------------------------------------- payloads

    def placement_info(self, query=None) -> dict:
        """``GET /placement``: the last computed plan (explained), the
        actuator's tallies, and what is currently steered/cooling."""
        a = self.actuator
        return {
            "enabled": True,
            "peer": str(self.server.peer_id),
            "interval_s": self.interval_s,
            "rounds": self.rounds,
            "lastImbalance": self.last_imbalance,
            "lastPlan": (self.last_plan.to_dict()
                         if self.last_plan is not None else None),
            "steeredPeers": sorted(self.server.read_steering.avoided()),
            "steeredReads": self.server.read_steering.steered,
            "cooldownGroups": sorted(a.cooldown_groups()),
            "transfersOk": a.transfers_ok,
            "transfersFailed": a.transfers_failed,
            "steerEpisodes": a.steers,
            "skipped": a.skipped,
        }
