"""The placement plan engine: cluster snapshot in, explainable plan out.

Pure scoring — no I/O, no server references — so the in-server policy
loop (controller.py, fed from the already-fetched ledger/sketch data)
and the ``shell rebalance`` frontend (fed from scraped ``/divisions``
``/lag`` ``/hotgroups`` ``/health`` payloads) compute the SAME plan from
the same facts.  O(servers + k) python per pass: the inputs are the
per-server rollups and the top-k sketch entries, never a divisions walk
(tools/check_hot_loops.py scans this package to keep it that way).

Scoring model (docs/placement.md):

- A group is **hot** when its sketch ``share_min`` (the guaranteed
  lower bound on its share of tracked commit load) is at least
  ``hot-share``.
- Each server's **fair share** of the hot set is ``ceil(hot /
  servers)``; a server leading more than ``fair + hysteresis`` hot
  groups sheds its hottest excess to the healthiest least-loaded peer.
  ``hysteresis`` is the anti-ping-pong band: after a transfer lands the
  source is AT fair share and the recipient is below the band, so the
  reverse move never plans.
- In the single-view in-server loop, hot-group shedding additionally
  requires live admission pressure (``shed_rate > 0``): sketch shares
  are relative to each server's OWN traffic, so the recipient of the
  fleet's hottest group sees it dominate a small local denominator and
  would otherwise bounce it straight back.  A server that isn't
  shedding requests has nothing for a transfer to relieve.
- A peer inside a watchdog grey episode, or scoring under
  ``grey-score`` on the lag ledger's health score, is steered away from
  as a readIndex confirmation target (and never picked as a transfer
  target).
- With a multi-server snapshot (the shell), a raw leadership-count
  spread beyond the hysteresis band plans one corrective transfer per
  round even when nothing crosses the hot-share floor.
- Shard-occupancy skew inside one server emits an ADVISORY
  ``RepinShard`` (printed with the plan; no repin actuator exists yet).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TransferLeadership:
    """Move ``group``'s leadership to ``to_peer``.  ``gid`` carries the
    RaftGroupId object on locally-built snapshots (the in-server
    actuator needs it; ``str(gid)`` is display-only and not parseable
    back); scraped snapshots leave it None and the shell resolves the
    display string through group_list."""
    group: str
    to_peer: str
    reason: str
    category: str = "hot-group"   # short slug for transfersIssued{reason=}
    gid: object = None

    kind = "transfer"


@dataclasses.dataclass(frozen=True)
class SteerReads:
    """Deprioritize ``away_from`` as a readIndex confirmation target
    (group "*": steering is a per-peer decision — the sweep applies it
    to every group that can still reach majority without the peer)."""
    away_from: str
    reason: str
    group: str = "*"
    category: str = "grey-steer"

    kind = "steer"


@dataclasses.dataclass(frozen=True)
class RepinShard:
    """ADVISORY: ``group`` would be better placed on loop shard
    ``shard``.  No repin actuator exists; the action is planned and
    printed so the skew is visible, never executed."""
    group: str
    shard: int
    reason: str
    category: str = "shard-skew"

    kind = "repin"


@dataclasses.dataclass(frozen=True)
class HotGroup:
    """One sketch entry as the policy sees it (from ``/hotgroups`` or
    straight off the sketch)."""
    group: str
    share: float = 0.0
    share_min: float = 0.0
    pending: int = 0
    led: bool = False            # does the viewing server lead it?
    shard: Optional[int] = None  # loop shard on the viewing server
    gid: object = None           # RaftGroupId object (local views only)


@dataclasses.dataclass
class ServerView:
    """One server's sensor rollup: everything the policy may consult,
    all O(peers + k) to build."""
    peer: str
    leading: int = 0
    pending_total: int = 0
    shed_total: int = 0
    shed_rate: float = 0.0
    divisions: int = 0
    shard_counts: tuple = ()         # divisions per loop shard (rollup)
    peer_scores: dict = dataclasses.field(default_factory=dict)
    grey_peers: frozenset = frozenset()
    hot_groups: tuple = ()           # HotGroup records, hottest first
    laggard_groups: tuple = ()       # /lag "groups" payload rows


@dataclasses.dataclass(frozen=True)
class ClusterSnapshot:
    """The policy input: one view per scraped server (the in-server loop
    runs on its own single view; the shell aggregates all of them)."""
    views: tuple

    def view(self, peer: str) -> Optional[ServerView]:
        for v in self.views:
            if v.peer == peer:
                return v
        return None


def view_from_payloads(peer: Optional[str] = None,
                       health: Optional[dict] = None,
                       lag: Optional[dict] = None,
                       hotgroups: Optional[dict] = None,
                       rollup: Optional[dict] = None,
                       grey=(), shed_rate: float = 0.0) -> ServerView:
    """Build one ServerView from the introspection payloads (any subset;
    the shell tolerates e.g. a 404 ``/hotgroups`` on a telemetry-off
    server).  The controller's local view takes the same shape, so both
    frontends score identical facts."""
    for src in (lag, rollup, health, hotgroups):
        if peer is None and src:
            peer = src.get("peer")
    v = ServerView(peer=str(peer or "?"), grey_peers=frozenset(grey),
                   shed_rate=shed_rate)
    if lag:
        v.leading = int(lag.get("leading", 0))
        v.peer_scores = {p["peer"]: float(p.get("score", 1.0))
                         for p in lag.get("peers", ())}
        v.laggard_groups = tuple(lag.get("groups", ()))
    if rollup:
        v.leading = int(rollup.get("leading", v.leading))
        v.pending_total = int(rollup.get("pendingTotal", 0))
        v.divisions = int(rollup.get("divisions", 0))
        v.shard_counts = tuple(rollup.get("shards", ()))
    if health:
        serving = health.get("serving", {})
        v.shed_total = int(serving.get("shedTotal", 0))
        if not v.pending_total:
            v.pending_total = int(serving.get("pendingCount", 0))
        if not v.divisions:
            v.divisions = int(health.get("divisions", 0))
    if hotgroups:
        v.hot_groups = tuple(
            HotGroup(group=g["group"], share=float(g.get("share", 0.0)),
                     share_min=float(g.get("share_min", 0.0)),
                     pending=int(g.get("pending", 0)),
                     led=bool(g.get("led", False)), shard=g.get("shard"))
            for g in hotgroups.get("groups", ()))
    return v


@dataclasses.dataclass
class PlacementPlan:
    """A typed, explainable round of actions.  ``imbalance`` is the
    round's headline gauge: max(hot-lead excess over fair share as a
    fraction of fair, multi-server leadership spread / mean); 0.0 = the
    policy sees nothing to move."""
    actions: list = dataclasses.field(default_factory=list)
    imbalance: float = 0.0
    notes: list = dataclasses.field(default_factory=list)

    def transfers(self) -> list:
        return [a for a in self.actions if a.kind == "transfer"]

    def steers(self) -> list:
        return [a for a in self.actions if a.kind == "steer"]

    def repins(self) -> list:
        return [a for a in self.actions if a.kind == "repin"]

    def explain(self) -> list:
        """Human lines, one per action + one per note — what the shell
        prints and ``GET /placement`` serves."""
        lines = []
        for a in self.actions:
            if a.kind == "transfer":
                lines.append(f"TRANSFER {a.group} -> {a.to_peer}: "
                             f"{a.reason}")
            elif a.kind == "steer":
                lines.append(f"STEER reads away from {a.away_from}: "
                             f"{a.reason}")
            else:
                lines.append(f"REPIN (advisory) {a.group} -> shard "
                             f"{a.shard}: {a.reason}")
        lines.extend(f"note: {n}" for n in self.notes)
        return lines

    def to_dict(self) -> dict:
        return {
            "imbalance": self.imbalance,
            "actions": [dataclasses.asdict(
                a, dict_factory=lambda kv: {k: v for k, v in kv
                                            if k != "gid"})
                        | {"kind": a.kind} for a in self.actions],
            "notes": list(self.notes),
            "explain": self.explain(),
        }


class PlacementPolicy:
    """The scoring pass.  Thresholds mirror ``raft.tpu.placement.*``;
    both frontends construct it from the same defaults so dry-run and
    the loop agree."""

    def __init__(self, *, hot_share: float = 0.2, grey_score: float = 0.5,
                 hysteresis: float = 1.0, max_transfers_per_round: int = 2):
        self.hot_share = hot_share
        self.grey_score = grey_score
        self.hysteresis = hysteresis
        self.max_transfers_per_round = max_transfers_per_round

    # ------------------------------------------------------------- scoring

    def _steer_targets(self, snapshot: ClusterSnapshot) -> list:
        """(peer, reason) for every peer the round should steer away
        from, deduped across views (grey episodes first — they carry the
        sharper diagnosis)."""
        out, seen = [], set()
        for v in snapshot.views:
            for name in sorted(v.grey_peers):
                if name not in seen:
                    seen.add(name)
                    out.append((name, f"grey-follower episode observed "
                                      f"by {v.peer}"))
        for v in snapshot.views:
            for name in sorted(v.peer_scores):
                score = v.peer_scores[name]
                if name in seen or name == v.peer:
                    continue
                if score < self.grey_score:
                    seen.add(name)
                    out.append((name, f"health score {score:.2f} < "
                                      f"{self.grey_score:.2f} "
                                      f"(view of {v.peer})"))
        return out

    def _candidates(self, snapshot: ClusterSnapshot, view: ServerView,
                    steered: set) -> list:
        """Transfer targets from ``view``, best first: healthy (not
        steered/grey, score >= grey-score), least-loaded when the
        snapshot knows other servers' leadership counts."""
        if len(snapshot.views) > 1:
            ranked = []
            for other in snapshot.views:
                name = other.peer
                if name == view.peer or name in steered \
                        or name in view.grey_peers:
                    continue
                score = view.peer_scores.get(name, 1.0)
                if score < self.grey_score:
                    continue
                ranked.append((other.leading, -score, name))
            return [r[2] for r in sorted(ranked)]
        ranked = []
        for name in sorted(view.peer_scores):
            score = view.peer_scores[name]
            if name == view.peer or name in steered \
                    or name in view.grey_peers or score < self.grey_score:
                continue
            ranked.append((-score, name))
        return [r[1] for r in sorted(ranked)]

    def plan(self, snapshot: ClusterSnapshot,
             exclude=()) -> PlacementPlan:
        """One scoring pass.  ``exclude`` is the actuator's cooldown set
        (group display strings): excluded groups are skipped WITH a
        note, and the per-round transfer cap is applied HERE so a
        dry-run prints exactly the plan the loop would execute."""
        plan = PlacementPlan()
        exclude = set(exclude)
        steered = set()
        for name, reason in self._steer_targets(snapshot):
            steered.add(name)
            plan.actions.append(SteerReads(away_from=name, reason=reason))

        # the cluster-wide hot set and each server's fair share of it
        hot_names = {g.group for v in snapshot.views for g in v.hot_groups
                     if g.share_min >= self.hot_share}
        n_servers = len(snapshot.views)
        if n_servers == 1:
            v = snapshot.views[0]
            n_servers = 1 + len([p for p in v.peer_scores if p != v.peer])
        fair = math.ceil(len(hot_names) / max(1, n_servers))
        hot_excess = 0
        transfers: list = []
        for v in snapshot.views:
            led_hot = sorted(
                (g for g in v.hot_groups
                 if g.led and g.share_min >= self.hot_share),
                key=lambda g: -g.share_min)
            excess = len(led_hot) - fair
            hot_excess = max(hot_excess, excess)
            if excess <= 0 or len(led_hot) <= fair + self.hysteresis:
                continue
            if len(snapshot.views) == 1 and v.shed_rate <= 0.0:
                # single-view guard against transfer ping-pong: each
                # server's sketch shares are relative to ITS OWN traffic,
                # so the server that just RECEIVED the fleet's hottest
                # group sees it dominate a small local denominator and
                # would bounce it straight back.  Shed leaderships only
                # while admission is actually shedding requests — the
                # pressure signal the transfer exists to relieve.  The
                # multi-view shell compares like with like and needs no
                # gate.
                plan.notes.append(
                    f"{v.peer} leads {len(led_hot)} hot group(s) (fair "
                    f"{fair}) but sheds no requests; holding until "
                    f"admission pressure shows")
                continue
            targets = self._candidates(snapshot, v, steered)
            if not targets:
                plan.notes.append(
                    f"{v.peer} leads {len(led_hot)} hot group(s) (fair "
                    f"{fair}) but no healthy transfer target exists")
                continue
            for i, g in enumerate(led_hot[:excess]):
                transfers.append(TransferLeadership(
                    group=g.group, to_peer=targets[i % len(targets)],
                    reason=(f"{v.peer} leads {len(led_hot)} hot groups "
                            f"(fair share {fair}); {g.group} share_min "
                            f"{g.share_min:.2f} >= {self.hot_share:.2f}"),
                    category="hot-group", gid=g.gid))

        # raw leadership-count spread (multi-server snapshots only): one
        # corrective move per round when nothing crossed hot-share
        lead_spread = 0.0
        if len(snapshot.views) > 1:
            leads = [v.leading for v in snapshot.views]
            mean = sum(leads) / len(leads)
            spread = max(leads) - min(leads)
            lead_spread = spread / max(1.0, mean)
            if not transfers and spread > max(1.0, self.hysteresis):
                src = max(snapshot.views, key=lambda v: v.leading)
                led_any = sorted((g for g in src.hot_groups if g.led),
                                 key=lambda g: -g.share_min)
                targets = self._candidates(snapshot, src, steered)
                if led_any and targets:
                    g = led_any[0]
                    transfers.append(TransferLeadership(
                        group=g.group, to_peer=targets[0],
                        reason=(f"leadership spread {max(leads)}-"
                                f"{min(leads)} > hysteresis "
                                f"{self.hysteresis:g}; moving "
                                f"{src.peer}'s busiest led group"),
                        category="leader-imbalance", gid=g.gid))

        kept = 0
        for t in transfers:
            if t.group in exclude:
                plan.notes.append(f"{t.group}: in cooldown, skipped")
                continue
            if kept >= self.max_transfers_per_round:
                plan.notes.append(
                    f"{t.group}: over max-transfers-per-round "
                    f"({self.max_transfers_per_round}), deferred")
                continue
            kept += 1
            plan.actions.append(t)

        # shard-occupancy skew -> advisory repin (never actuated)
        for v in snapshot.views:
            if len(v.shard_counts) > 1:
                hi = max(range(len(v.shard_counts)),
                         key=lambda i: v.shard_counts[i])
                lo = min(range(len(v.shard_counts)),
                         key=lambda i: v.shard_counts[i])
                if v.shard_counts[hi] - v.shard_counts[lo] <= 1:
                    continue
                on_hi = [g for g in v.hot_groups if g.shard == hi]
                if on_hi:
                    plan.actions.append(RepinShard(
                        group=on_hi[0].group, shard=lo,
                        reason=(f"{v.peer} shard occupancy "
                                f"{list(v.shard_counts)}: shard {hi} "
                                f"carries {v.shard_counts[hi]} divisions "
                                f"vs {v.shard_counts[lo]}")))

        plan.imbalance = round(max(
            hot_excess / max(1, fair) if hot_excess > 0 else 0.0,
            lead_spread), 4)
        return plan
