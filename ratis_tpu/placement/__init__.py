"""Placement subsystem: telemetry-driven rebalancing that closes the
control loop (reference analog: TiKV's Placement Driver pattern over
exactly this multi-raft shape — telemetry-scored leadership transfers
and read steering on a host carrying many groups).

Three pieces share one plan engine:

- :mod:`ratis_tpu.placement.policy` — the pure scoring pass: a cluster
  snapshot (leadership counts, shed, per-peer health scores, laggards,
  hot groups) in, a typed explainable :class:`PlacementPlan` out.
- :mod:`ratis_tpu.placement.actuate` — rate-limited execution through
  the existing admin transfer path plus the readIndex steering hook,
  every actuation journaled as a paired watchdog rebalance event.
- :mod:`ratis_tpu.placement.controller` — the opt-in in-server policy
  loop (``raft.tpu.placement.enabled``; unset = nothing is created)
  with its ``placement_plane`` metric registry and ``GET /placement``.

The ``shell rebalance`` subcommand (ratis_tpu.shell.cli) is the second
frontend: it builds the same snapshot from scraped endpoints and prints
the same plan the loop executes, with reasons.
"""

from ratis_tpu.placement.policy import (ClusterSnapshot,  # noqa: F401
                                        PlacementPlan, PlacementPolicy,
                                        RepinShard, ServerView,
                                        SteerReads, TransferLeadership,
                                        view_from_payloads)
from ratis_tpu.placement.actuate import PlacementActuator  # noqa: F401
from ratis_tpu.placement.controller import (  # noqa: F401
    PlacementController)
