"""Live property reconfiguration.

Capability parity with the reference's Reconfigurable surface (the
reconfiguration protocol hadoop-lineage servers expose; in Apache Ratis the
pattern appears as runtime-adjustable knobs consulted through suppliers
rather than constructor-frozen fields).  Round-1 review flagged that every
component here read its properties once at construction; this module gives
the server a registry of reconfigurable listeners so an operator can adjust
runtime-tunable keys on a live server:

    server.reconfiguration.reconfigure("raft.server.rpc.slowness.timeout",
                                       "30s")

Keys not claimed by any listener are rejected, mirroring the reference's
ReconfigurationException for unknown/immutable properties.
"""

from __future__ import annotations

import logging
from typing import Awaitable, Callable, Optional

LOG = logging.getLogger(__name__)


class ReconfigurationException(Exception):
    pass


class ReconfigurationManager:
    """Per-server registry: key -> list of async apply(key, new_value)."""

    def __init__(self, properties):
        self.properties = properties
        self._handlers: dict[str, list[Callable[[str, Optional[str]],
                                                Awaitable[None]]]] = {}

    def register(self, key: str,
                 apply: Callable[[str, Optional[str]], Awaitable[None]]
                 ) -> None:
        self._handlers.setdefault(key, []).append(apply)

    def unregister_all(self, keys: list[str], apply) -> None:
        for key in keys:
            handlers = self._handlers.get(key)
            if handlers and apply in handlers:
                handlers.remove(apply)

    def reconfigurable_properties(self) -> list[str]:
        return sorted(self._handlers)

    async def reconfigure(self, key: str, value: Optional[str]) -> None:
        """Set the property and notify every registered listener.  Raises
        ReconfigurationException for keys nothing consumes at runtime —
        silently 'accepting' them would lie to the operator."""
        handlers = self._handlers.get(key)
        if not handlers:
            raise ReconfigurationException(
                f"property {key!r} is not reconfigurable at runtime "
                f"(reconfigurable: {self.reconfigurable_properties()})")
        old = self.properties.get(key)
        if value is None:
            self.properties.unset(key)
        else:
            self.properties.set(key, value)
        try:
            for apply in list(handlers):
                await apply(key, value)
        except Exception:
            # roll the stored value back so properties reflect what is live
            if old is not None:
                self.properties.set(key, old)
            else:
                self.properties.unset(key)
            raise
        LOG.info("reconfigured %s: %r -> %r", key, old, value)
