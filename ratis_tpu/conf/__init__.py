from ratis_tpu.conf.properties import Parameters, RaftProperties, parse_size
from ratis_tpu.conf.keys import RaftClientConfigKeys, RaftConfigKeys, RaftServerConfigKeys
