"""Config key catalogs with defaults.

Capability parity with the reference's *ConfigKeys interfaces
(ratis-server-api/.../RaftServerConfigKeys.java:43-961, RaftClientConfigKeys,
RaftConfigKeys): PREFIX-composed dotted keys with typed defaults.  Layout
follows the reference's nested namespaces (Rpc, Log, Log.Appender, Snapshot,
Read, Write, Watch, RetryCache, LeaderElection, Notification, ThreadPool),
plus a new `Engine` namespace for the TPU batched-quorum engine.
"""

from __future__ import annotations

from ratis_tpu.conf.properties import RaftProperties
from ratis_tpu.util.timeduration import TimeDuration


class RaftConfigKeys:
    PREFIX = "raft"

    class Rpc:
        TYPE_KEY = "raft.rpc.type"
        TYPE_DEFAULT = "SIMULATED"  # transports: SIMULATED | GRPC

        @staticmethod
        def type(p: RaftProperties) -> str:
            return p.get(RaftConfigKeys.Rpc.TYPE_KEY, RaftConfigKeys.Rpc.TYPE_DEFAULT).upper()

        @staticmethod
        def set_type(p: RaftProperties, t: str) -> None:
            p.set(RaftConfigKeys.Rpc.TYPE_KEY, t.upper())


class RaftServerConfigKeys:
    PREFIX = "raft.server"

    STORAGE_DIR_KEY = "raft.server.storage.dir"
    STORAGE_DIR_DEFAULT = "/tmp/ratis-tpu"
    STORAGE_FREE_SPACE_MIN_KEY = "raft.server.storage.free-space.min"
    STORAGE_FREE_SPACE_MIN_DEFAULT = "0MB"
    # setConfiguration staging: a bootstrapping peer is "caught up" once it is
    # within this many entries of the leader's last index (reference
    # RaftServerConfigKeys stagingCatchupGap, used by LeaderStateImpl
    # checkStaging:828).
    STAGING_CATCHUP_GAP_KEY = "raft.server.staging.catchup.gap"
    STAGING_CATCHUP_GAP_DEFAULT = 1000

    # Host-runtime loop sharding (no reference analog; the closest shape is
    # Netty's NioEventLoopGroup): run this many worker event loops per
    # RaftServer and hash-pin each Division — its request handling,
    # appenders, heartbeat sweep share, and outbound transport connections —
    # to one of them.  1 (the default) = the single-loop runtime, with no
    # dispatch indirection anywhere.  The traced decomposition that
    # motivates >1 is in docs/perf.md ("Per-stage residual": ready-callback
    # queueing on one saturated loop dominates the north-star shape).
    LOOP_SHARDS_KEY = "raft.tpu.server.loop-shards"
    LOOP_SHARDS_DEFAULT = 1

    @staticmethod
    def loop_shards(p: RaftProperties) -> int:
        return p.get_int(RaftServerConfigKeys.LOOP_SHARDS_KEY,
                         RaftServerConfigKeys.LOOP_SHARDS_DEFAULT)

    @staticmethod
    def storage_dirs(p: RaftProperties) -> list[str]:
        v = p.get(RaftServerConfigKeys.STORAGE_DIR_KEY,
                  RaftServerConfigKeys.STORAGE_DIR_DEFAULT)
        return [s.strip() for s in v.split(",") if s.strip()]

    @staticmethod
    def set_storage_dir(p: RaftProperties, dirs: "list[str] | str") -> None:
        if isinstance(dirs, list):
            dirs = ",".join(dirs)
        p.set(RaftServerConfigKeys.STORAGE_DIR_KEY, dirs)

    class Rpc:
        # Election timeout bounds; each follower randomizes in [min, max)
        # (reference Rpc.TIMEOUT_MIN/MAX, RaftServerConfigKeys.java).
        TIMEOUT_MIN_KEY = "raft.server.rpc.timeout.min"
        TIMEOUT_MIN_DEFAULT = TimeDuration.millis(150)
        TIMEOUT_MAX_KEY = "raft.server.rpc.timeout.max"
        TIMEOUT_MAX_DEFAULT = TimeDuration.millis(300)
        REQUEST_TIMEOUT_KEY = "raft.server.rpc.request.timeout"
        REQUEST_TIMEOUT_DEFAULT = TimeDuration.millis(3000)
        SLEEP_TIME_KEY = "raft.server.rpc.sleep.time"
        SLEEP_TIME_DEFAULT = TimeDuration.millis(25)
        SLOWNESS_TIMEOUT_KEY = "raft.server.rpc.slowness.timeout"
        SLOWNESS_TIMEOUT_DEFAULT = TimeDuration.valueOf("60s")

        @staticmethod
        def timeout_min(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Rpc.TIMEOUT_MIN_KEY,
                                       RaftServerConfigKeys.Rpc.TIMEOUT_MIN_DEFAULT)

        @staticmethod
        def timeout_max(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Rpc.TIMEOUT_MAX_KEY,
                                       RaftServerConfigKeys.Rpc.TIMEOUT_MAX_DEFAULT)

        @staticmethod
        def request_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_KEY,
                                       RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_DEFAULT)

        @staticmethod
        def slowness_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Rpc.SLOWNESS_TIMEOUT_KEY,
                                       RaftServerConfigKeys.Rpc.SLOWNESS_TIMEOUT_DEFAULT)

        @staticmethod
        def set_timeout(p: RaftProperties, tmin, tmax) -> None:
            p.set_time_duration(RaftServerConfigKeys.Rpc.TIMEOUT_MIN_KEY, tmin)
            p.set_time_duration(RaftServerConfigKeys.Rpc.TIMEOUT_MAX_KEY, tmax)

    class Log:
        USE_MEMORY_KEY = "raft.server.log.use.memory"
        USE_MEMORY_DEFAULT = False
        SEGMENT_SIZE_MAX_KEY = "raft.server.log.segment.size.max"
        SEGMENT_SIZE_MAX_DEFAULT = "8MB"
        PREALLOCATED_SIZE_KEY = "raft.server.log.preallocated.size"
        PREALLOCATED_SIZE_DEFAULT = "4MB"
        WRITE_BUFFER_SIZE_KEY = "raft.server.log.write.buffer.size"
        WRITE_BUFFER_SIZE_DEFAULT = "64KB"
        FORCE_SYNC_NUM_KEY = "raft.server.log.force.sync.num"
        FORCE_SYNC_NUM_DEFAULT = 128
        UNSAFE_FLUSH_ENABLED_KEY = "raft.server.log.unsafe-flush.enabled"
        UNSAFE_FLUSH_ENABLED_DEFAULT = False
        PURGE_GAP_KEY = "raft.server.log.purge.gap"
        PURGE_GAP_DEFAULT = 1024
        PURGE_UPTO_SNAPSHOT_INDEX_KEY = "raft.server.log.purge.upto.snapshot.index"
        PURGE_UPTO_SNAPSHOT_INDEX_DEFAULT = False
        SEGMENT_CACHE_NUM_MAX_KEY = "raft.server.log.segment.cache.num.max"
        SEGMENT_CACHE_NUM_MAX_DEFAULT = 6
        QUEUE_ELEMENT_LIMIT_KEY = "raft.server.log.queue.element-limit"
        QUEUE_ELEMENT_LIMIT_DEFAULT = 4096
        QUEUE_BYTE_LIMIT_KEY = "raft.server.log.queue.byte-limit"
        QUEUE_BYTE_LIMIT_DEFAULT = "64MB"

        @staticmethod
        def use_memory(p: RaftProperties) -> bool:
            return p.get_boolean(RaftServerConfigKeys.Log.USE_MEMORY_KEY,
                                 RaftServerConfigKeys.Log.USE_MEMORY_DEFAULT)

        @staticmethod
        def set_use_memory(p: RaftProperties, v: bool) -> None:
            p.set_boolean(RaftServerConfigKeys.Log.USE_MEMORY_KEY, v)

        @staticmethod
        def segment_size_max(p: RaftProperties) -> int:
            return p.get_size(RaftServerConfigKeys.Log.SEGMENT_SIZE_MAX_KEY,
                              RaftServerConfigKeys.Log.SEGMENT_SIZE_MAX_DEFAULT)

        @staticmethod
        def segment_cache_num_max(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Log.SEGMENT_CACHE_NUM_MAX_KEY,
                RaftServerConfigKeys.Log.SEGMENT_CACHE_NUM_MAX_DEFAULT)

        @staticmethod
        def force_sync_num(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Log.FORCE_SYNC_NUM_KEY,
                             RaftServerConfigKeys.Log.FORCE_SYNC_NUM_DEFAULT)

        @staticmethod
        def purge_gap(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Log.PURGE_GAP_KEY,
                             RaftServerConfigKeys.Log.PURGE_GAP_DEFAULT)

        class Appender:
            BUFFER_BYTE_LIMIT_KEY = "raft.server.log.appender.buffer.byte-limit"
            BUFFER_BYTE_LIMIT_DEFAULT = "4MB"
            BUFFER_ELEMENT_LIMIT_KEY = "raft.server.log.appender.buffer.element-limit"
            BUFFER_ELEMENT_LIMIT_DEFAULT = 0  # 0 = unlimited
            SNAPSHOT_CHUNK_SIZE_MAX_KEY = "raft.server.log.appender.snapshot.chunk.size.max"
            SNAPSHOT_CHUNK_SIZE_MAX_DEFAULT = "16MB"
            INSTALL_SNAPSHOT_ENABLED_KEY = "raft.server.log.appender.install.snapshot.enabled"
            INSTALL_SNAPSHOT_ENABLED_DEFAULT = True
            PIPELINE_WINDOW_KEY = "raft.server.log.appender.pipeline.window"
            PIPELINE_WINDOW_DEFAULT = 16  # in-flight AppendEntries per follower
            WAIT_TIME_MIN_KEY = "raft.server.log.appender.wait-time.min"
            WAIT_TIME_MIN_DEFAULT = TimeDuration.millis(10)
            # Data-path coalescing (no reference analog — the reference runs
            # one stream per (group, follower), GrpcLogAppender.java:356):
            # fold every group's append batches toward one destination into
            # a single AppendEnvelope RPC per flush.  Disabled = one unary
            # RPC per batch (the reference's cost shape).
            COALESCING_ENABLED_KEY = "raft.server.log.appender.coalescing.enabled"
            COALESCING_ENABLED_DEFAULT = True
            ENVELOPE_INFLIGHT_KEY = "raft.server.log.appender.envelope.inflight"
            ENVELOPE_INFLIGHT_DEFAULT = 4  # concurrent envelopes per peer
            ENVELOPE_BYTE_LIMIT_KEY = "raft.server.log.appender.envelope.byte-limit"
            ENVELOPE_BYTE_LIMIT_DEFAULT = "8MB"

            @staticmethod
            def buffer_byte_limit(p: RaftProperties) -> int:
                return p.get_size(
                    RaftServerConfigKeys.Log.Appender.BUFFER_BYTE_LIMIT_KEY,
                    RaftServerConfigKeys.Log.Appender.BUFFER_BYTE_LIMIT_DEFAULT)

            @staticmethod
            def install_snapshot_enabled(p: RaftProperties) -> bool:
                return p.get_boolean(
                    RaftServerConfigKeys.Log.Appender.INSTALL_SNAPSHOT_ENABLED_KEY,
                    RaftServerConfigKeys.Log.Appender.INSTALL_SNAPSHOT_ENABLED_DEFAULT)

            @staticmethod
            def pipeline_window(p: RaftProperties) -> int:
                return p.get_int(
                    RaftServerConfigKeys.Log.Appender.PIPELINE_WINDOW_KEY,
                    RaftServerConfigKeys.Log.Appender.PIPELINE_WINDOW_DEFAULT)

            @staticmethod
            def coalescing_enabled(p: RaftProperties) -> bool:
                return p.get_boolean(
                    RaftServerConfigKeys.Log.Appender.COALESCING_ENABLED_KEY,
                    RaftServerConfigKeys.Log.Appender.COALESCING_ENABLED_DEFAULT)

            @staticmethod
            def envelope_inflight(p: RaftProperties) -> int:
                return p.get_int(
                    RaftServerConfigKeys.Log.Appender.ENVELOPE_INFLIGHT_KEY,
                    RaftServerConfigKeys.Log.Appender.ENVELOPE_INFLIGHT_DEFAULT)

            @staticmethod
            def envelope_byte_limit(p: RaftProperties) -> int:
                return p.get_size(
                    RaftServerConfigKeys.Log.Appender.ENVELOPE_BYTE_LIMIT_KEY,
                    RaftServerConfigKeys.Log.Appender.ENVELOPE_BYTE_LIMIT_DEFAULT)

    class Snapshot:
        AUTO_TRIGGER_ENABLED_KEY = "raft.server.snapshot.auto.trigger.enabled"
        AUTO_TRIGGER_ENABLED_DEFAULT = False
        AUTO_TRIGGER_THRESHOLD_KEY = "raft.server.snapshot.auto.trigger.threshold"
        AUTO_TRIGGER_THRESHOLD_DEFAULT = 400000
        CREATION_GAP_KEY = "raft.server.snapshot.creation.gap"
        CREATION_GAP_DEFAULT = 1024
        RETENTION_FILE_NUM_KEY = "raft.server.snapshot.retention.file.num"
        RETENTION_FILE_NUM_DEFAULT = -1

        @staticmethod
        def auto_trigger_enabled(p: RaftProperties) -> bool:
            return p.get_boolean(RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_ENABLED_KEY,
                                 RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_ENABLED_DEFAULT)

        @staticmethod
        def auto_trigger_threshold(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_THRESHOLD_KEY,
                             RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_THRESHOLD_DEFAULT)

        @staticmethod
        def creation_gap(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Snapshot.CREATION_GAP_KEY,
                             RaftServerConfigKeys.Snapshot.CREATION_GAP_DEFAULT)

        @staticmethod
        def retention_file_num(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Snapshot.RETENTION_FILE_NUM_KEY,
                             RaftServerConfigKeys.Snapshot.RETENTION_FILE_NUM_DEFAULT)

    class Read:
        class Option:
            DEFAULT = "DEFAULT"  # reads served from leader state directly
            LINEARIZABLE = "LINEARIZABLE"  # readIndex protocol

        OPTION_KEY = "raft.server.read.option"
        OPTION_DEFAULT = "DEFAULT"
        TIMEOUT_KEY = "raft.server.read.timeout"
        TIMEOUT_DEFAULT = TimeDuration.valueOf("10s")
        LEADER_LEASE_ENABLED_KEY = "raft.server.read.leader.lease.enabled"
        LEADER_LEASE_ENABLED_DEFAULT = False
        LEADER_LEASE_TIMEOUT_RATIO_KEY = "raft.server.read.leader.lease.timeout.ratio"
        LEADER_LEASE_TIMEOUT_RATIO_DEFAULT = 0.9
        READ_AFTER_WRITE_CONSISTENT_TIMEOUT_KEY = \
            "raft.server.read.read-after-write-consistent.write-index-cache.expiry-time"
        READ_AFTER_WRITE_CONSISTENT_TIMEOUT_DEFAULT = TimeDuration.valueOf("60s")

        @staticmethod
        def option(p: RaftProperties) -> str:
            return p.get(RaftServerConfigKeys.Read.OPTION_KEY,
                         RaftServerConfigKeys.Read.OPTION_DEFAULT).upper()

        @staticmethod
        def timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Read.TIMEOUT_KEY,
                                       RaftServerConfigKeys.Read.TIMEOUT_DEFAULT)

        @staticmethod
        def leader_lease_enabled(p: RaftProperties) -> bool:
            return p.get_boolean(RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_KEY,
                                 RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_DEFAULT)

        @staticmethod
        def leader_lease_timeout_ratio(p: RaftProperties) -> float:
            return p.get_float(RaftServerConfigKeys.Read.LEADER_LEASE_TIMEOUT_RATIO_KEY,
                               RaftServerConfigKeys.Read.LEADER_LEASE_TIMEOUT_RATIO_DEFAULT)

    class Write:
        ELEMENT_LIMIT_KEY = "raft.server.write.element-limit"
        ELEMENT_LIMIT_DEFAULT = 4096
        BYTE_LIMIT_KEY = "raft.server.write.byte-limit"
        BYTE_LIMIT_DEFAULT = "64MB"
        FOLLOWER_GAP_RATIO_MAX_KEY = "raft.server.write.follower.gap.ratio.max"
        FOLLOWER_GAP_RATIO_MAX_DEFAULT = -1.0

        @staticmethod
        def element_limit(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Write.ELEMENT_LIMIT_KEY,
                             RaftServerConfigKeys.Write.ELEMENT_LIMIT_DEFAULT)

        @staticmethod
        def byte_limit(p: RaftProperties) -> int:
            return p.get_size(RaftServerConfigKeys.Write.BYTE_LIMIT_KEY,
                              RaftServerConfigKeys.Write.BYTE_LIMIT_DEFAULT)

    class Watch:
        ELEMENT_LIMIT_KEY = "raft.server.watch.element-limit"
        ELEMENT_LIMIT_DEFAULT = 65536
        TIMEOUT_KEY = "raft.server.watch.timeout"
        TIMEOUT_DEFAULT = TimeDuration.valueOf("10s")
        TIMEOUT_DENOMINATION_KEY = "raft.server.watch.timeout.denomination"
        TIMEOUT_DENOMINATION_DEFAULT = TimeDuration.valueOf("1s")

        @staticmethod
        def timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Watch.TIMEOUT_KEY,
                                       RaftServerConfigKeys.Watch.TIMEOUT_DEFAULT)

        @staticmethod
        def element_limit(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Watch.ELEMENT_LIMIT_KEY,
                             RaftServerConfigKeys.Watch.ELEMENT_LIMIT_DEFAULT)

    class RetryCache:
        EXPIRY_TIME_KEY = "raft.server.retrycache.expiry-time"
        EXPIRY_TIME_DEFAULT = TimeDuration.valueOf("60s")
        STATISTICS_EXPIRY_TIME_KEY = "raft.server.retrycache.statistics.expiry-time"
        STATISTICS_EXPIRY_TIME_DEFAULT = TimeDuration.valueOf("100us")

        @staticmethod
        def expiry_time(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.RetryCache.EXPIRY_TIME_KEY,
                                       RaftServerConfigKeys.RetryCache.EXPIRY_TIME_DEFAULT)

    class LeaderElection:
        LEADER_STEP_DOWN_WAIT_TIME_KEY = "raft.server.leaderelection.leader.step-down.wait-time"
        LEADER_STEP_DOWN_WAIT_TIME_DEFAULT = TimeDuration.valueOf("10s")
        PRE_VOTE_KEY = "raft.server.leaderelection.pre-vote"
        PRE_VOTE_DEFAULT = True
        MEMBER_MAJORITY_ADD_KEY = "raft.server.leaderelection.member.majority.add"
        MEMBER_MAJORITY_ADD_DEFAULT = False

        @staticmethod
        def pre_vote(p: RaftProperties) -> bool:
            return p.get_boolean(RaftServerConfigKeys.LeaderElection.PRE_VOTE_KEY,
                                 RaftServerConfigKeys.LeaderElection.PRE_VOTE_DEFAULT)

        @staticmethod
        def step_down_wait_time(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.LeaderElection.LEADER_STEP_DOWN_WAIT_TIME_KEY,
                RaftServerConfigKeys.LeaderElection.LEADER_STEP_DOWN_WAIT_TIME_DEFAULT)

    class Heartbeat:
        """Multi-raft bulk heartbeats (no reference analog — removes the
        reference's O(groups) per-interval heartbeat volume): the sweep
        ships ONE compact BulkHeartbeat per destination server per interval
        instead of one AppendEntries per (group, follower).  Disabled =
        unary per-group heartbeats, the reference's cost shape."""

        COALESCING_ENABLED_KEY = "raft.tpu.heartbeat.coalescing.enabled"
        COALESCING_ENABLED_DEFAULT = True

        @staticmethod
        def coalescing_enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Heartbeat.COALESCING_ENABLED_KEY,
                RaftServerConfigKeys.Heartbeat.COALESCING_ENABLED_DEFAULT)

    class Hibernate:
        """Idle-group quiescence (no reference analog; the multi-raft
        production pattern TiKV calls hibernate regions): a leader whose
        group has no pending work and fully-synced followers stops
        heartbeating it, and its followers disarm their election timers —
        an idle group costs ZERO background traffic.  Any contact (client
        request, append, vote) wakes the group; the availability trade is
        that a leader dying while hibernated is only detected at the next
        contact.  Requires heartbeat coalescing (the hibernate handshake
        rides the compact bulk items); OFF by default."""

        ENABLED_KEY = "raft.tpu.hibernate.enabled"
        ENABLED_DEFAULT = False
        # quiet sweeps before a group hibernates
        AFTER_SWEEPS_KEY = "raft.tpu.hibernate.after-sweeps"
        AFTER_SWEEPS_DEFAULT = 4
        # Dead-leader backstop: a hibernated follower arms this (long)
        # election deadline instead of disarming outright, and the sleeping
        # leader sends ONE hibernate-flagged heartbeat per backstop/4 to
        # keep refreshing it.  A dead leader stops refreshing, so the group
        # becomes electable again within ~backstop even with zero client
        # traffic.  "0s" restores the round-4 full-disarm behavior.
        BACKSTOP_KEY = "raft.tpu.hibernate.backstop"
        BACKSTOP_DEFAULT = "60s"

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Hibernate.ENABLED_KEY,
                RaftServerConfigKeys.Hibernate.ENABLED_DEFAULT)

        @staticmethod
        def after_sweeps(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Hibernate.AFTER_SWEEPS_KEY,
                RaftServerConfigKeys.Hibernate.AFTER_SWEEPS_DEFAULT)

        @staticmethod
        def backstop(p: RaftProperties):
            return p.get_time_duration(
                RaftServerConfigKeys.Hibernate.BACKSTOP_KEY,
                RaftServerConfigKeys.Hibernate.BACKSTOP_DEFAULT)

    class Upkeep:
        """Vectorized upkeep plane (server/upkeep.py): per-loop-shard
        packed deadline arrays replace the per-group Python walk in the
        heartbeat sweep, hibernation backstop, retry-cache/write-index
        expiry, client-window sweep, and watch-frontier refresh.  OFF by
        default; unset reproduces the per-group paths bit-for-bit."""

        ENABLED_KEY = "raft.tpu.upkeep.enabled"
        ENABLED_DEFAULT = False
        # Full-walk resync cadence (sweeps): every N sweeps the plane
        # re-derives every registered division's deadlines from scratch —
        # an O(G) backstop against a missed re-arm hook.  At the default
        # 64 sweeps (~5s at the 75ms sweep cadence) the amortized cost is
        # negligible; 0 disables the resync.
        RESYNC_SWEEPS_KEY = "raft.tpu.upkeep.resync-sweeps"
        RESYNC_SWEEPS_DEFAULT = 64

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Upkeep.ENABLED_KEY,
                RaftServerConfigKeys.Upkeep.ENABLED_DEFAULT)

        @staticmethod
        def resync_sweeps(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Upkeep.RESYNC_SWEEPS_KEY,
                RaftServerConfigKeys.Upkeep.RESYNC_SWEEPS_DEFAULT)

    class Metrics:
        """Per-server introspection endpoint (the cluster observability
        plane's scrape surface; no 1:1 reference analog — the reference
        exposes dropwizard reporters, operators today scrape Prometheus).
        When the port key is SET the server serves ``GET /metrics``
        (Prometheus text), ``/health`` (liveness + engine tick freshness),
        ``/divisions`` (per-division introspection JSON), and ``/events``
        (the stall watchdog's journal) on 127.0.0.1.  ``0`` binds an
        ephemeral port (the multi-process bench children use it and report
        the bound port to the parent); UNSET (the default) opens no
        listener socket and leaves the request hot paths untouched."""

        HTTP_PORT_KEY = "raft.tpu.metrics.http-port"

        @staticmethod
        def http_port(p: RaftProperties) -> "int | None":
            v = p.get(RaftServerConfigKeys.Metrics.HTTP_PORT_KEY)
            return None if v in (None, "") else int(v)

    class Watchdog:
        """Stall watchdog (ratis_tpu.server.watchdog; no reference analog —
        the closest shape is Borgmon-style derived alerting): a per-server
        sampling task detecting commit-stall (commitIndex flat while
        pending requests > 0), election churn, and follower lag beyond a
        threshold.  Detections append structured events to a bounded ring
        journal served at ``GET /events`` and surfaced by the shell's
        ``health`` subcommand.  Pure background sampling — nothing on the
        request path."""

        ENABLED_KEY = "raft.tpu.watchdog.enabled"
        ENABLED_DEFAULT = True
        INTERVAL_KEY = "raft.tpu.watchdog.interval"
        INTERVAL_DEFAULT = TimeDuration.valueOf("1s")
        JOURNAL_SIZE_KEY = "raft.tpu.watchdog.journal-size"
        JOURNAL_SIZE_DEFAULT = 256
        # follower match-index lag (entries behind the leader commit)
        # beyond which a follower-lag event is journaled
        FOLLOWER_LAG_KEY = "raft.tpu.watchdog.follower-lag-threshold"
        FOLLOWER_LAG_DEFAULT = 4096
        # election timeouts + started elections per sampling interval
        # (server-wide) beyond which an election-churn event is journaled
        CHURN_KEY = "raft.tpu.watchdog.churn-threshold"
        CHURN_DEFAULT = 8

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Watchdog.ENABLED_KEY,
                RaftServerConfigKeys.Watchdog.ENABLED_DEFAULT)

        @staticmethod
        def interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Watchdog.INTERVAL_KEY,
                RaftServerConfigKeys.Watchdog.INTERVAL_DEFAULT)

        @staticmethod
        def journal_size(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Watchdog.JOURNAL_SIZE_KEY,
                RaftServerConfigKeys.Watchdog.JOURNAL_SIZE_DEFAULT)

        @staticmethod
        def follower_lag_threshold(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Watchdog.FOLLOWER_LAG_KEY,
                RaftServerConfigKeys.Watchdog.FOLLOWER_LAG_DEFAULT)

        @staticmethod
        def churn_threshold(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Watchdog.CHURN_KEY,
                RaftServerConfigKeys.Watchdog.CHURN_DEFAULT)

    class Telemetry:
        """Continuous telemetry (ratis_tpu.metrics.timeseries /
        ratis_tpu.metrics.flight; reference analog: the per-server
        rate/percentile registries of ratis-metrics,
        RaftServerMetricsImpl — operators see trends, not samples).  A
        per-server background sampler takes registry deltas at a fixed
        cadence into bounded ring buffers, derives rates (commits/s,
        acks/s, rewinds/s, engine occupancy) and log2-bucket latency
        quantiles, and tracks a space-saving top-k hot-group sketch
        (commits + pending per group) served at ``GET /timeseries``
        (``?since=`` incremental) and ``GET /hotgroups``.  The flight
        recorder keeps the last window of samples + watchdog events +
        recent trace spans and dumps a replayable JSON artifact on
        watchdog degradation, chaos scenario failure, SIGTERM, or
        explicit request (``GET /flightrecorder``).  With ``enabled``
        unset (the default) no sampler task is created and every
        request path is untouched."""

        ENABLED_KEY = "raft.tpu.telemetry.enabled"
        ENABLED_DEFAULT = False
        INTERVAL_KEY = "raft.tpu.telemetry.interval"
        INTERVAL_DEFAULT = TimeDuration.valueOf("1s")
        # ring window: samples retained = window / interval (bounded)
        WINDOW_KEY = "raft.tpu.telemetry.window"
        WINDOW_DEFAULT = TimeDuration.valueOf("120s")
        # space-saving sketch size: top-k hot groups tracked exactly
        # enough (error bound <= total/k rides along in the payload)
        HOT_GROUPS_KEY = "raft.tpu.telemetry.hot-groups"
        HOT_GROUPS_DEFAULT = 16
        # flight-recorder artifacts land here; "" = serve /flightrecorder
        # on request but never write dump files on triggers
        FLIGHT_DIR_KEY = "raft.tpu.telemetry.flight-dir"
        FLIGHT_DIR_DEFAULT = ""

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Telemetry.ENABLED_KEY,
                RaftServerConfigKeys.Telemetry.ENABLED_DEFAULT)

        @staticmethod
        def interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Telemetry.INTERVAL_KEY,
                RaftServerConfigKeys.Telemetry.INTERVAL_DEFAULT)

        @staticmethod
        def window(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Telemetry.WINDOW_KEY,
                RaftServerConfigKeys.Telemetry.WINDOW_DEFAULT)

        @staticmethod
        def hot_groups(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Telemetry.HOT_GROUPS_KEY,
                RaftServerConfigKeys.Telemetry.HOT_GROUPS_DEFAULT)

        @staticmethod
        def flight_dir(p: RaftProperties) -> str:
            return p.get(RaftServerConfigKeys.Telemetry.FLIGHT_DIR_KEY,
                         RaftServerConfigKeys.Telemetry.FLIGHT_DIR_DEFAULT)

    class Serving:
        """Production serving plane (ratis_tpu.server.serving; reference
        analogs: RaftServerImpl's pending-request element/byte limits and
        resource checks, ReadRequests' readIndex machinery).  Two halves:
        admission control bounds the pending intake per loop shard (count
        and bytes) and sheds overflow with a typed
        ResourceUnavailableException carrying a retry-after hint, so a
        saturated shard degrades into fast typed rejections instead of a
        p99 collapse; the batched-read scheduler coalesces the readIndex
        leadership-confirmation round across every group with pending
        linearizable reads on a shard into one zero-entry append envelope
        per destination peer, amortizing the per-group heartbeat round the
        same way the quorum engine amortizes per-group math.  Admission is
        off by default (every request admitted); read batching is on by
        default and falls back to the scalar per-group confirmation when
        disabled."""

        ADMISSION_ENABLED_KEY = "raft.tpu.serving.admission.enabled"
        ADMISSION_ENABLED_DEFAULT = False
        # per-loop-shard bounds on requests admitted but not yet replied
        PENDING_ELEMENT_LIMIT_KEY = "raft.tpu.serving.admission.pending.element-limit"
        PENDING_ELEMENT_LIMIT_DEFAULT = 8192
        PENDING_BYTE_LIMIT_KEY = "raft.tpu.serving.admission.pending.byte-limit"
        PENDING_BYTE_LIMIT_DEFAULT = "64MB"
        # base retry-after hint carried in shed replies; scaled by overshoot
        RETRY_AFTER_KEY = "raft.tpu.serving.admission.retry-after"
        RETRY_AFTER_DEFAULT = TimeDuration.valueOf("200ms")
        READ_BATCH_ENABLED_KEY = "raft.tpu.serving.read-batch.enabled"
        READ_BATCH_ENABLED_DEFAULT = True
        # extra coalescing delay before a confirmation sweep fires; 0 =
        # coalesce only what arrives in the same event-loop pass
        READ_BATCH_WINDOW_KEY = "raft.tpu.serving.read-batch.window"
        READ_BATCH_WINDOW_DEFAULT = TimeDuration.valueOf("0ms")
        # sustained shed rate (sheds/s over a watchdog interval) above
        # which an overload event is journaled and health degrades
        OVERLOAD_SHED_RATE_KEY = "raft.tpu.serving.overload.shed-rate"
        OVERLOAD_SHED_RATE_DEFAULT = 50.0

        @staticmethod
        def admission_enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Serving.ADMISSION_ENABLED_KEY,
                RaftServerConfigKeys.Serving.ADMISSION_ENABLED_DEFAULT)

        @staticmethod
        def pending_element_limit(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Serving.PENDING_ELEMENT_LIMIT_KEY,
                RaftServerConfigKeys.Serving.PENDING_ELEMENT_LIMIT_DEFAULT)

        @staticmethod
        def pending_byte_limit(p: RaftProperties) -> int:
            return p.get_size(
                RaftServerConfigKeys.Serving.PENDING_BYTE_LIMIT_KEY,
                RaftServerConfigKeys.Serving.PENDING_BYTE_LIMIT_DEFAULT)

        @staticmethod
        def retry_after(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Serving.RETRY_AFTER_KEY,
                RaftServerConfigKeys.Serving.RETRY_AFTER_DEFAULT)

        @staticmethod
        def read_batch_enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Serving.READ_BATCH_ENABLED_KEY,
                RaftServerConfigKeys.Serving.READ_BATCH_ENABLED_DEFAULT)

        @staticmethod
        def read_batch_window(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Serving.READ_BATCH_WINDOW_KEY,
                RaftServerConfigKeys.Serving.READ_BATCH_WINDOW_DEFAULT)

        @staticmethod
        def overload_shed_rate(p: RaftProperties) -> float:
            return p.get_float(
                RaftServerConfigKeys.Serving.OVERLOAD_SHED_RATE_KEY,
                RaftServerConfigKeys.Serving.OVERLOAD_SHED_RATE_DEFAULT)

    class Lag:
        """Lag & health ledger (ratis_tpu.engine.ledger; reference analog:
        RaftServerMetrics' per-follower lag gauges on the Metrics SPI,
        here batched over the ``[G, P]`` arrays into one fused pass per
        telemetry tick).  ``threshold`` is the follower-lag line in
        entries-behind-commit shared by the watchdog detector and the
        grey classifier; ``up-window`` separates *grey* (slow but acking)
        from *down* (not acking at all).  The ``grey.*`` knobs shape the
        grey-follower episode detector: a peer is grey when at least
        ``grey.fraction`` of its active links (up links of groups that
        advanced commit this pass, at least ``grey.min-groups`` of them)
        are past the threshold for ``grey.rounds`` consecutive watchdog
        samples while none of its links are down."""

        THRESHOLD_KEY = "raft.tpu.lag.threshold"
        THRESHOLD_DEFAULT = 64
        UP_WINDOW_KEY = "raft.tpu.lag.up-window"
        UP_WINDOW_DEFAULT = TimeDuration.valueOf("3s")
        GREY_FRACTION_KEY = "raft.tpu.lag.grey.fraction"
        GREY_FRACTION_DEFAULT = 0.6
        GREY_MIN_GROUPS_KEY = "raft.tpu.lag.grey.min-groups"
        GREY_MIN_GROUPS_DEFAULT = 4
        GREY_ROUNDS_KEY = "raft.tpu.lag.grey.rounds"
        GREY_ROUNDS_DEFAULT = 2
        # laggard-group list size in GET /lag (and shell lag)
        TOP_GROUPS_KEY = "raft.tpu.lag.top-groups"
        TOP_GROUPS_DEFAULT = 8

        @staticmethod
        def threshold(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Lag.THRESHOLD_KEY,
                             RaftServerConfigKeys.Lag.THRESHOLD_DEFAULT)

        @staticmethod
        def up_window(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Lag.UP_WINDOW_KEY,
                RaftServerConfigKeys.Lag.UP_WINDOW_DEFAULT)

        @staticmethod
        def grey_fraction(p: RaftProperties) -> float:
            return p.get_float(
                RaftServerConfigKeys.Lag.GREY_FRACTION_KEY,
                RaftServerConfigKeys.Lag.GREY_FRACTION_DEFAULT)

        @staticmethod
        def grey_min_groups(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Lag.GREY_MIN_GROUPS_KEY,
                RaftServerConfigKeys.Lag.GREY_MIN_GROUPS_DEFAULT)

        @staticmethod
        def grey_rounds(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Lag.GREY_ROUNDS_KEY,
                RaftServerConfigKeys.Lag.GREY_ROUNDS_DEFAULT)

        @staticmethod
        def top_groups(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Lag.TOP_GROUPS_KEY,
                RaftServerConfigKeys.Lag.TOP_GROUPS_DEFAULT)

    class Placement:
        """Placement controller (ratis_tpu.placement; reference analog:
        TiKV's Placement Driver pattern over exactly this shape —
        telemetry-scored leadership transfers and read steering on a
        multi-raft host).  ``enabled`` unset (the default) creates
        nothing: no loop, no registry, identical request paths.  When
        on, one scoring pass per ``interval`` consumes the
        already-fetched ledger/sketch data (O(servers + k) python, no
        divisions walk), emits an explainable plan, and actuates it
        rate-limited: at most ``max-transfers-per-round`` leadership
        transfers, each group then held out for ``cooldown``;
        ``hysteresis`` is the extra hot-leads margin a server must
        exceed over its fair share before it sheds (the anti-ping-pong
        band).  ``hot-share`` is the sketch share_min floor for a group
        to count as hot; peers scoring under ``grey-score`` (or inside
        a watchdog grey episode) are steered away from as readIndex
        confirmation targets for ``steer-ttl`` per actuation."""

        ENABLED_KEY = "raft.tpu.placement.enabled"
        ENABLED_DEFAULT = False
        INTERVAL_KEY = "raft.tpu.placement.interval"
        INTERVAL_DEFAULT = TimeDuration.valueOf("2s")
        MAX_TRANSFERS_KEY = "raft.tpu.placement.max-transfers-per-round"
        MAX_TRANSFERS_DEFAULT = 2
        COOLDOWN_KEY = "raft.tpu.placement.cooldown"
        COOLDOWN_DEFAULT = TimeDuration.valueOf("30s")
        HYSTERESIS_KEY = "raft.tpu.placement.hysteresis"
        HYSTERESIS_DEFAULT = 1.0
        HOT_SHARE_KEY = "raft.tpu.placement.hot-share"
        HOT_SHARE_DEFAULT = 0.2
        GREY_SCORE_KEY = "raft.tpu.placement.grey-score"
        GREY_SCORE_DEFAULT = 0.5
        STEER_TTL_KEY = "raft.tpu.placement.steer-ttl"
        STEER_TTL_DEFAULT = TimeDuration.valueOf("10s")
        TRANSFER_TIMEOUT_KEY = "raft.tpu.placement.transfer-timeout"
        TRANSFER_TIMEOUT_DEFAULT = TimeDuration.valueOf("3s")

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Placement.ENABLED_KEY,
                RaftServerConfigKeys.Placement.ENABLED_DEFAULT)

        @staticmethod
        def interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Placement.INTERVAL_KEY,
                RaftServerConfigKeys.Placement.INTERVAL_DEFAULT)

        @staticmethod
        def max_transfers(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Placement.MAX_TRANSFERS_KEY,
                RaftServerConfigKeys.Placement.MAX_TRANSFERS_DEFAULT)

        @staticmethod
        def cooldown(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Placement.COOLDOWN_KEY,
                RaftServerConfigKeys.Placement.COOLDOWN_DEFAULT)

        @staticmethod
        def hysteresis(p: RaftProperties) -> float:
            return p.get_float(
                RaftServerConfigKeys.Placement.HYSTERESIS_KEY,
                RaftServerConfigKeys.Placement.HYSTERESIS_DEFAULT)

        @staticmethod
        def hot_share(p: RaftProperties) -> float:
            return p.get_float(
                RaftServerConfigKeys.Placement.HOT_SHARE_KEY,
                RaftServerConfigKeys.Placement.HOT_SHARE_DEFAULT)

        @staticmethod
        def grey_score(p: RaftProperties) -> float:
            return p.get_float(
                RaftServerConfigKeys.Placement.GREY_SCORE_KEY,
                RaftServerConfigKeys.Placement.GREY_SCORE_DEFAULT)

        @staticmethod
        def steer_ttl(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Placement.STEER_TTL_KEY,
                RaftServerConfigKeys.Placement.STEER_TTL_DEFAULT)

        @staticmethod
        def transfer_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Placement.TRANSFER_TIMEOUT_KEY,
                RaftServerConfigKeys.Placement.TRANSFER_TIMEOUT_DEFAULT)

    class Chaos:
        """Chaos campaign subsystem (ratis_tpu.chaos; reference analogs:
        RaftExceptionBaseTest, the kill/restart suites over simulated RPC,
        CodeInjectionForTesting): deterministic, seed-replayable fault
        scenarios — link partitions/latency/drop via the transport shim,
        crash/restart with tail truncation, slow-disk/slow-follower
        injection, leader-churn storms — each asserting recovery SLOs and
        journaling every injected fault through the watchdog ``/events``
        plane.  With ``enabled`` unset (the default) no transport ever
        consults the link-fault table and the request paths are
        untouched."""

        ENABLED_KEY = "raft.tpu.chaos.enabled"
        ENABLED_DEFAULT = False
        SEED_KEY = "raft.tpu.chaos.seed"
        SEED_DEFAULT = 0
        # re-election convergence SLO: after a fault heals, every affected
        # group must have a ready leader within this bound
        CONVERGENCE_TIMEOUT_KEY = "raft.tpu.chaos.convergence-timeout"
        CONVERGENCE_TIMEOUT_DEFAULT = TimeDuration.valueOf("30s")
        # post-heal quiesce SLO: replication + apply must drain (commit ==
        # applied on every live replica) within this bound
        RECOVERY_TIMEOUT_KEY = "raft.tpu.chaos.recovery-timeout"
        RECOVERY_TIMEOUT_DEFAULT = TimeDuration.valueOf("120s")
        # failing scenarios write their (seed, scenario, journal) replay
        # artifact here; "" = don't write artifacts
        ARTIFACT_DIR_KEY = "raft.tpu.chaos.artifact-dir"
        ARTIFACT_DIR_DEFAULT = ""

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Chaos.ENABLED_KEY,
                RaftServerConfigKeys.Chaos.ENABLED_DEFAULT)

        @staticmethod
        def seed(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Chaos.SEED_KEY,
                             RaftServerConfigKeys.Chaos.SEED_DEFAULT)

        @staticmethod
        def convergence_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Chaos.CONVERGENCE_TIMEOUT_KEY,
                RaftServerConfigKeys.Chaos.CONVERGENCE_TIMEOUT_DEFAULT)

        @staticmethod
        def recovery_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Chaos.RECOVERY_TIMEOUT_KEY,
                RaftServerConfigKeys.Chaos.RECOVERY_TIMEOUT_DEFAULT)

        @staticmethod
        def artifact_dir(p: RaftProperties) -> str:
            return p.get(RaftServerConfigKeys.Chaos.ARTIFACT_DIR_KEY,
                         RaftServerConfigKeys.Chaos.ARTIFACT_DIR_DEFAULT)

    class PauseMonitor:
        """Event-loop pause monitor (reference JvmPauseMonitor.java:38)."""

        ENABLED_KEY = "raft.server.pause.monitor.enabled"
        ENABLED_DEFAULT = True
        INTERVAL_KEY = "raft.server.pause.monitor.interval"
        INTERVAL_DEFAULT = TimeDuration.millis(100)
        WARN_KEY = "raft.server.pause.monitor.warn.threshold"
        WARN_DEFAULT = TimeDuration.millis(500)

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.PauseMonitor.ENABLED_KEY,
                RaftServerConfigKeys.PauseMonitor.ENABLED_DEFAULT)

        @staticmethod
        def interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.PauseMonitor.INTERVAL_KEY,
                RaftServerConfigKeys.PauseMonitor.INTERVAL_DEFAULT)

        @staticmethod
        def warn_threshold(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.PauseMonitor.WARN_KEY,
                RaftServerConfigKeys.PauseMonitor.WARN_DEFAULT)

    class Gc:
        """Heap discipline for multi-raft hosts (ratis_tpu.util.gcdiscipline;
        no reference analog — CPython's gen-2 collector over a 10k-group
        heap measured a 52s pause, enough for the pause monitor to depose
        every leader on the server).  Opt-in: tunes GC thresholds at
        server start and, once the group set has been idle for
        ``freeze-idle``, runs one deliberate full collection and freezes
        the surviving heap out of the collector."""

        DISCIPLINE_KEY = "raft.tpu.gc.discipline"
        DISCIPLINE_DEFAULT = False
        FREEZE_IDLE_KEY = "raft.tpu.gc.freeze-idle"
        FREEZE_IDLE_DEFAULT = TimeDuration.valueOf("10s")
        # Steady-state re-seal cadence (0 = off).  A loaded multi-raft host
        # accretes long-lived objects (log entries) that are never garbage
        # but are walked by every young-gen pass: measured at 5-peer x
        # 10240 groups, gen-1 collections burned 0.3-0.5s each COLLECTING
        # ZERO.  Periodic re-freezing moves the accreted live set out of
        # the collector.  Trade (document before enabling): frozen objects
        # are never reclaimed, so workloads that DROP long-lived state
        # (log purge after snapshot) leak it until close.
        REFREEZE_INTERVAL_KEY = "raft.tpu.gc.refreeze-interval"
        REFREEZE_INTERVAL_DEFAULT = TimeDuration.valueOf("0s")

        @staticmethod
        def discipline(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Gc.DISCIPLINE_KEY,
                RaftServerConfigKeys.Gc.DISCIPLINE_DEFAULT)

        @staticmethod
        def freeze_idle(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Gc.FREEZE_IDLE_KEY,
                RaftServerConfigKeys.Gc.FREEZE_IDLE_DEFAULT)

        @staticmethod
        def refreeze_interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Gc.REFREEZE_INTERVAL_KEY,
                RaftServerConfigKeys.Gc.REFREEZE_INTERVAL_DEFAULT)

    class Trace:
        """Host-path tracing (ratis_tpu.trace; no reference analog — the
        reference leans on JVM profilers): per-stage request->commit spans
        recorded into fixed-size ring buffers, exportable as a percentile
        decomposition table and Chrome trace-event JSON (Perfetto).  OFF by
        default; when enabled, every ``sample-every``-th client request is
        traced end to end and process-level stages (rpc codec, engine
        dispatch) sample at the same rate."""

        ENABLED_KEY = "raft.tpu.trace.enabled"
        ENABLED_DEFAULT = False
        SAMPLE_EVERY_KEY = "raft.tpu.trace.sample-every"
        SAMPLE_EVERY_DEFAULT = 16
        RING_SIZE_KEY = "raft.tpu.trace.ring-size"
        RING_SIZE_DEFAULT = 4096

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(
                RaftServerConfigKeys.Trace.ENABLED_KEY,
                RaftServerConfigKeys.Trace.ENABLED_DEFAULT)

        @staticmethod
        def sample_every(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Trace.SAMPLE_EVERY_KEY,
                RaftServerConfigKeys.Trace.SAMPLE_EVERY_DEFAULT)

        @staticmethod
        def ring_size(p: RaftProperties) -> int:
            return p.get_int(
                RaftServerConfigKeys.Trace.RING_SIZE_KEY,
                RaftServerConfigKeys.Trace.RING_SIZE_DEFAULT)

    class Notification:
        NO_LEADER_TIMEOUT_KEY = "raft.server.notification.no-leader.timeout"
        NO_LEADER_TIMEOUT_DEFAULT = TimeDuration.valueOf("60s")

        @staticmethod
        def no_leader_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftServerConfigKeys.Notification.NO_LEADER_TIMEOUT_KEY,
                RaftServerConfigKeys.Notification.NO_LEADER_TIMEOUT_DEFAULT)

    class Replication:
        """Replication-plane batching knobs (new; no reference analog —
        the reference schedules one GrpcLogAppender daemon per (group,
        follower)).  The sweep discipline converts the replication hot
        path from per-request/per-group scheduling to batched sweeps:
        one drain pass per (destination, loop-shard) collects due
        AppendEntries across ALL co-hosted groups, follower ack frames
        batch-decode into one packed engine intake, and commit fan-out
        resolves client waiters through a per-division waterline with one
        scheduled callback per connection instead of one wakeup chain per
        request."""

        # Master switch.  0 reproduces the exact per-request paths of the
        # pre-sweep runtime: per-appender wake->collect->schedule flush
        # loops, scalar QuorumEngine.on_ack per follower reply, and
        # per-request reply-future wakeup chains.
        SWEEP_KEY = "raft.tpu.replication.sweep"
        SWEEP_DEFAULT = 1
        # Commit fan-out collapse (requires sweep=1): resolve client
        # waiters via the per-division commit waterline and deliver
        # replies through the transport's per-connection batcher (one
        # scheduled callback per connection per batch).  0 keeps the
        # per-request reply-future chain while the append sweep and
        # packed ack intake stay on.
        REPLY_FANOUT_KEY = "raft.tpu.replication.reply-fanout"
        REPLY_FANOUT_DEFAULT = 1
        # Pin DataStream packet handling (stream accept/packet-read work)
        # to the owning division's loop shard instead of the primary loop
        # (the attributed structural cause of mixed-rung stream
        # starvation, docs/perf.md).  Only meaningful with
        # raft.tpu.server.loop-shards > 1; 0 keeps the primary-loop path.
        STREAM_SHARDS_KEY = "raft.tpu.replication.stream-shards"
        STREAM_SHARDS_DEFAULT = 1
        # Sequenced append-window pipelining (round 9, reference analog:
        # GrpcLogAppender's per-follower sliding window,
        # GrpcLogAppender.java:343-381, batched across groups): a group may
        # contribute entries to up to this many consecutive in-flight
        # multi-group frames per (destination, loop-shard) lane.  Frames
        # carry lane/sequence numbers and the follower's sweep intake
        # processes them in lane order, so per-group FIFO no longer needs
        # the one-frame-per-group busy latch.  1 = exactly the latched
        # (stop-and-wait per group) behavior — the deterministic fallback
        # and the scalar-reference cost shape.  Only effective with
        # sweep=1 and appender coalescing on.
        WINDOW_DEPTH_KEY = "raft.tpu.replication.window-depth"
        WINDOW_DEPTH_DEFAULT = 4
        # Follower-side lane intake: frames parked past a sequence HOLE
        # (a lower seq never arrived) are briefly buffered — up to this
        # many per lane — waiting for the gap to fill; beyond it (or
        # after the gap wait times out) the frame is rejected with a
        # rewind hint and the sender re-cuts the lane.  In-order frames
        # queued behind a busy predecessor are ordinary pipelining,
        # bounded separately (RaftServer._LANE_QUEUE_MAX).
        REORDER_BUFFER_KEY = "raft.tpu.replication.reorder-buffer"
        REORDER_BUFFER_DEFAULT = 8

        @staticmethod
        def sweep(p: RaftProperties) -> bool:
            return p.get_int(
                RaftServerConfigKeys.Replication.SWEEP_KEY,
                RaftServerConfigKeys.Replication.SWEEP_DEFAULT) > 0

        @staticmethod
        def reply_fanout(p: RaftProperties) -> bool:
            return p.get_int(
                RaftServerConfigKeys.Replication.REPLY_FANOUT_KEY,
                RaftServerConfigKeys.Replication.REPLY_FANOUT_DEFAULT) > 0

        @staticmethod
        def stream_shards(p: RaftProperties) -> bool:
            return p.get_int(
                RaftServerConfigKeys.Replication.STREAM_SHARDS_KEY,
                RaftServerConfigKeys.Replication.STREAM_SHARDS_DEFAULT) > 0

        @staticmethod
        def window_depth(p: RaftProperties) -> int:
            return max(1, p.get_int(
                RaftServerConfigKeys.Replication.WINDOW_DEPTH_KEY,
                RaftServerConfigKeys.Replication.WINDOW_DEPTH_DEFAULT))

        @staticmethod
        def reorder_buffer(p: RaftProperties) -> int:
            return max(1, p.get_int(
                RaftServerConfigKeys.Replication.REORDER_BUFFER_KEY,
                RaftServerConfigKeys.Replication.REORDER_BUFFER_DEFAULT))

    class TpuLog:
        """Shared log plane (new; no reference analog — the reference gives
        every group its own segment files).  With ``raft.tpu.log.shared``
        on, all divisions pinned to a loop shard interleave into one
        per-shard segment sequence so a replication sweep costs one
        buffered write + one fsync regardless of group count.  Unset
        keeps the per-group segmented store bit-for-bit."""

        SHARED_KEY = "raft.tpu.log.shared"
        SHARED_DEFAULT = 0
        # Roll the interleaved segment at this size.  Larger than the
        # per-group default (8MB): one shard file absorbs every co-hosted
        # group's traffic.
        SHARED_SEGMENT_SIZE_MAX_KEY = "raft.tpu.log.shared.segment.size.max"
        SHARED_SEGMENT_SIZE_MAX_DEFAULT = "32MB"
        # Rewrite a sealed segment once at least this fraction of its bytes
        # is dead (tombstoned / purged / overwritten records).
        COMPACTION_DEAD_RATIO_KEY = "raft.tpu.log.shared.compaction.dead-ratio"
        COMPACTION_DEAD_RATIO_DEFAULT = 0.5

        @staticmethod
        def shared(p: RaftProperties) -> bool:
            return p.get_int(
                RaftServerConfigKeys.TpuLog.SHARED_KEY,
                RaftServerConfigKeys.TpuLog.SHARED_DEFAULT) > 0

        @staticmethod
        def set_shared(p: RaftProperties, v: bool) -> None:
            p.set_int(RaftServerConfigKeys.TpuLog.SHARED_KEY, 1 if v else 0)

        @staticmethod
        def shared_segment_size_max(p: RaftProperties) -> int:
            return p.get_size(
                RaftServerConfigKeys.TpuLog.SHARED_SEGMENT_SIZE_MAX_KEY,
                RaftServerConfigKeys.TpuLog.SHARED_SEGMENT_SIZE_MAX_DEFAULT)

        @staticmethod
        def compaction_dead_ratio(p: RaftProperties) -> float:
            return min(1.0, max(0.05, p.get_float(
                RaftServerConfigKeys.TpuLog.COMPACTION_DEAD_RATIO_KEY,
                RaftServerConfigKeys.TpuLog.COMPACTION_DEAD_RATIO_DEFAULT)))

    class Engine:
        """TPU batched-quorum engine knobs (new; no reference analog — this
        replaces the reference's thread-per-division daemons)."""

        TICK_INTERVAL_KEY = "raft.tpu.engine.tick-interval"
        TICK_INTERVAL_DEFAULT = TimeDuration.millis(2)
        MAX_GROUPS_KEY = "raft.tpu.engine.max-groups"
        MAX_GROUPS_DEFAULT = 1024
        MAX_PEERS_KEY = "raft.tpu.engine.max-peers"
        MAX_PEERS_DEFAULT = 8
        SCALAR_FALLBACK_THRESHOLD_KEY = "raft.tpu.engine.scalar-fallback-threshold"
        SCALAR_FALLBACK_THRESHOLD_DEFAULT = 16  # below this many groups, skip device dispatch
        PLATFORM_KEY = "raft.tpu.engine.platform"
        PLATFORM_DEFAULT = ""  # "" = jax default platform
        # Shard the resident engine state over this many local devices
        # (jax.sharding.Mesh over the group axis; ratis_tpu.parallel.mesh).
        # 0 = single-device.  Each device owns one contiguous slice of the
        # group batch and receives only its slice's packed events; group
        # capacity is auto-padded up to the next mesh multiple (padded
        # rows stay masked invalid), so any max-groups value is legal.
        MESH_DEVICES_KEY = "raft.tpu.engine.mesh-devices"
        MESH_DEVICES_DEFAULT = 0
        # When set, the engine runs inside a jax.profiler trace written to
        # this directory (XLA device ops + one named step per tick, for
        # TensorBoard/xprof).  Empty = no profiling.  SURVEY §5 tracing.
        PROFILE_DIR_KEY = "raft.tpu.engine.profile-dir"
        PROFILE_DIR_DEFAULT = ""

        @staticmethod
        def tick_interval(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftServerConfigKeys.Engine.TICK_INTERVAL_KEY,
                                       RaftServerConfigKeys.Engine.TICK_INTERVAL_DEFAULT)

        @staticmethod
        def max_groups(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Engine.MAX_GROUPS_KEY,
                             RaftServerConfigKeys.Engine.MAX_GROUPS_DEFAULT)

        @staticmethod
        def max_peers(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Engine.MAX_PEERS_KEY,
                             RaftServerConfigKeys.Engine.MAX_PEERS_DEFAULT)

        @staticmethod
        def mesh_devices(p: RaftProperties) -> int:
            return p.get_int(RaftServerConfigKeys.Engine.MESH_DEVICES_KEY,
                             RaftServerConfigKeys.Engine.MESH_DEVICES_DEFAULT)

        @staticmethod
        def profile_dir(p: RaftProperties) -> str:
            return p.get(RaftServerConfigKeys.Engine.PROFILE_DIR_KEY,
                         RaftServerConfigKeys.Engine.PROFILE_DIR_DEFAULT)


class GrpcConfigKeys:
    """gRPC transport keys (reference GrpcConfigKeys, ratis-grpc/.../
    GrpcConfigKeys.java; TLS block maps GrpcTlsConfig)."""

    PREFIX = "raft.grpc"

    # Separate client/admin plane endpoint (reference GrpcConfigKeys.Client/
    # Admin port split, GrpcServicesImpl.java:197): when set, client requests
    # are served on this port while server-to-server RPC stays on the main
    # address. "" = share the main port.
    CLIENT_PORT_KEY = "raft.grpc.client.port"

    # Dedicated ADMIN endpoint (the reference optionally runs THREE gRPC
    # servers — server/client/admin — each with its own TLS,
    # GrpcServicesImpl.java:56,197-224).  When set, admin request types are
    # served on this port (and ONLY admin types; data-plane requests are
    # rejected there).  "" = admin shares the client (or main) endpoint.
    ADMIN_PORT_KEY = "raft.grpc.admin.port"

    @staticmethod
    def client_port(p: RaftProperties):
        v = p.get(GrpcConfigKeys.CLIENT_PORT_KEY)
        return int(v) if v else None

    @staticmethod
    def admin_port(p: RaftProperties):
        v = p.get(GrpcConfigKeys.ADMIN_PORT_KEY)
        return int(v) if v else None

    class Tls:
        ENABLED_KEY = "raft.grpc.tls.enabled"
        ENABLED_DEFAULT = False
        CERT_CHAIN_KEY = "raft.grpc.tls.cert.chain.path"
        PRIVATE_KEY_KEY = "raft.grpc.tls.private.key.path"
        TRUST_ROOT_KEY = "raft.grpc.tls.trust.root.path"
        MUTUAL_AUTH_KEY = "raft.grpc.tls.mutual.auth.enabled"
        MUTUAL_AUTH_DEFAULT = False
        NAME_OVERRIDE_KEY = "raft.grpc.tls.target.name.override"

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(GrpcConfigKeys.Tls.ENABLED_KEY,
                                 GrpcConfigKeys.Tls.ENABLED_DEFAULT)

        @staticmethod
        def cert_chain(p: RaftProperties):
            return p.get(GrpcConfigKeys.Tls.CERT_CHAIN_KEY)

        @staticmethod
        def private_key(p: RaftProperties):
            return p.get(GrpcConfigKeys.Tls.PRIVATE_KEY_KEY)

        @staticmethod
        def trust_root(p: RaftProperties):
            return p.get(GrpcConfigKeys.Tls.TRUST_ROOT_KEY)

        @staticmethod
        def mutual_auth(p: RaftProperties) -> bool:
            return p.get_boolean(GrpcConfigKeys.Tls.MUTUAL_AUTH_KEY,
                                 GrpcConfigKeys.Tls.MUTUAL_AUTH_DEFAULT)

        @staticmethod
        def name_override(p: RaftProperties):
            return p.get(GrpcConfigKeys.Tls.NAME_OVERRIDE_KEY)

    class AdminTls:
        """Admin-endpoint TLS override (the reference's admin server takes
        its own GrpcTlsConfig, GrpcServicesImpl.java:56,219-224).  When not
        enabled, the admin endpoint inherits the main Tls block."""

        ENABLED_KEY = "raft.grpc.admin.tls.enabled"
        ENABLED_DEFAULT = False
        CERT_CHAIN_KEY = "raft.grpc.admin.tls.cert.chain.path"
        PRIVATE_KEY_KEY = "raft.grpc.admin.tls.private.key.path"
        TRUST_ROOT_KEY = "raft.grpc.admin.tls.trust.root.path"
        MUTUAL_AUTH_KEY = "raft.grpc.admin.tls.mutual.auth.enabled"
        MUTUAL_AUTH_DEFAULT = False

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(GrpcConfigKeys.AdminTls.ENABLED_KEY,
                                 GrpcConfigKeys.AdminTls.ENABLED_DEFAULT)

        @staticmethod
        def cert_chain(p: RaftProperties):
            return p.get(GrpcConfigKeys.AdminTls.CERT_CHAIN_KEY)

        @staticmethod
        def private_key(p: RaftProperties):
            return p.get(GrpcConfigKeys.AdminTls.PRIVATE_KEY_KEY)

        @staticmethod
        def trust_root(p: RaftProperties):
            return p.get(GrpcConfigKeys.AdminTls.TRUST_ROOT_KEY)

        @staticmethod
        def mutual_auth(p: RaftProperties) -> bool:
            return p.get_boolean(GrpcConfigKeys.AdminTls.MUTUAL_AUTH_KEY,
                                 GrpcConfigKeys.AdminTls.MUTUAL_AUTH_DEFAULT)


class WireConfigKeys:
    """Wire hot-path write coalescing (no reference analog — the reference
    pays one Netty/HTTP2 flush per message and amortizes via one stream per
    (group, follower), GrpcLogAppender.java:343-381; this framework folds
    RPCs instead, so the per-frame ``write()+drain()`` syscall pair became
    the next measured wall).  A per-connection send queue batches pending
    frames into ONE buffered flush once ``flush-bytes`` are pending or
    ``flush-micros`` of latency budget has elapsed (0µs = flush at the next
    event-loop pass, which batches everything enqueued in the current pass
    at zero added latency).  Both thresholds 0 (the default) = the exact
    per-frame write+drain path, byte-identical on the wire."""

    class Tcp:
        FLUSH_BYTES_KEY = "raft.tpu.tcp.flush-bytes"
        FLUSH_BYTES_DEFAULT = "0B"  # 0 = per-frame (coalescing off)
        FLUSH_MICROS_KEY = "raft.tpu.tcp.flush-micros"
        FLUSH_MICROS_DEFAULT = 0

        @staticmethod
        def flush_bytes(p: RaftProperties) -> int:
            return p.get_size(WireConfigKeys.Tcp.FLUSH_BYTES_KEY,
                              WireConfigKeys.Tcp.FLUSH_BYTES_DEFAULT)

        @staticmethod
        def flush_micros(p: RaftProperties) -> int:
            return p.get_int(WireConfigKeys.Tcp.FLUSH_MICROS_KEY,
                             WireConfigKeys.Tcp.FLUSH_MICROS_DEFAULT)

    class Grpc:
        """Stream-framing coalescing for the grpc.aio transport: one bidi
        stream message carries up to ``flush-chunks`` append/request chunks
        (VERDICT r5 item 6 — grpc.aio's per-message Python+C-core cost was
        the residual gap vs TCP), gathered for at most ``flush-micros``.
        0µs = coalescing off: one chunk per stream message, the wire shape
        of previous rounds."""

        FLUSH_MICROS_KEY = "raft.tpu.grpc.flush-micros"
        FLUSH_MICROS_DEFAULT = 0
        FLUSH_CHUNKS_KEY = "raft.tpu.grpc.flush-chunks"
        FLUSH_CHUNKS_DEFAULT = 64

        @staticmethod
        def flush_micros(p: RaftProperties) -> int:
            return p.get_int(WireConfigKeys.Grpc.FLUSH_MICROS_KEY,
                             WireConfigKeys.Grpc.FLUSH_MICROS_DEFAULT)

        @staticmethod
        def flush_chunks(p: RaftProperties) -> int:
            return p.get_int(WireConfigKeys.Grpc.FLUSH_CHUNKS_KEY,
                             WireConfigKeys.Grpc.FLUSH_CHUNKS_DEFAULT)


class NettyConfigKeys:
    """Raw-TCP (netty-analog) transport keys (reference NettyConfigKeys,
    ratis-netty/.../NettyConfigKeys.java; the TLS block mirrors what the
    reference's gRPC transport gets from GrpcTlsConfig — the netty analog
    here supports TLS so no transport is plaintext-only)."""

    PREFIX = "raft.netty"

    class Tls:
        ENABLED_KEY = "raft.netty.tls.enabled"
        ENABLED_DEFAULT = False
        CERT_CHAIN_KEY = "raft.netty.tls.cert.chain.path"
        PRIVATE_KEY_KEY = "raft.netty.tls.private.key.path"
        TRUST_ROOT_KEY = "raft.netty.tls.trust.root.path"
        MUTUAL_AUTH_KEY = "raft.netty.tls.mutual.auth.enabled"
        MUTUAL_AUTH_DEFAULT = False

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(NettyConfigKeys.Tls.ENABLED_KEY,
                                 NettyConfigKeys.Tls.ENABLED_DEFAULT)

        @staticmethod
        def cert_chain(p: RaftProperties):
            return p.get(NettyConfigKeys.Tls.CERT_CHAIN_KEY)

        @staticmethod
        def private_key(p: RaftProperties):
            return p.get(NettyConfigKeys.Tls.PRIVATE_KEY_KEY)

        @staticmethod
        def trust_root(p: RaftProperties):
            return p.get(NettyConfigKeys.Tls.TRUST_ROOT_KEY)

        @staticmethod
        def mutual_auth(p: RaftProperties) -> bool:
            return p.get_boolean(NettyConfigKeys.Tls.MUTUAL_AUTH_KEY,
                                 NettyConfigKeys.Tls.MUTUAL_AUTH_DEFAULT)

    class DataStreamTls:
        """TLS for the DataStream transport (reference NettyServerStreamRpc
        takes its own TlsConfig, ratis-netty/.../NettyServerStreamRpc.java);
        separate block because the stream plane often terminates TLS
        differently from the RPC plane."""

        ENABLED_KEY = "raft.datastream.tls.enabled"
        ENABLED_DEFAULT = False
        CERT_CHAIN_KEY = "raft.datastream.tls.cert.chain.path"
        PRIVATE_KEY_KEY = "raft.datastream.tls.private.key.path"
        TRUST_ROOT_KEY = "raft.datastream.tls.trust.root.path"
        MUTUAL_AUTH_KEY = "raft.datastream.tls.mutual.auth.enabled"
        MUTUAL_AUTH_DEFAULT = False

        @staticmethod
        def enabled(p: RaftProperties) -> bool:
            return p.get_boolean(NettyConfigKeys.DataStreamTls.ENABLED_KEY,
                                 NettyConfigKeys.DataStreamTls.ENABLED_DEFAULT)

        @staticmethod
        def cert_chain(p: RaftProperties):
            return p.get(NettyConfigKeys.DataStreamTls.CERT_CHAIN_KEY)

        @staticmethod
        def private_key(p: RaftProperties):
            return p.get(NettyConfigKeys.DataStreamTls.PRIVATE_KEY_KEY)

        @staticmethod
        def trust_root(p: RaftProperties):
            return p.get(NettyConfigKeys.DataStreamTls.TRUST_ROOT_KEY)

        @staticmethod
        def mutual_auth(p: RaftProperties) -> bool:
            return p.get_boolean(
                NettyConfigKeys.DataStreamTls.MUTUAL_AUTH_KEY,
                NettyConfigKeys.DataStreamTls.MUTUAL_AUTH_DEFAULT)

        @staticmethod
        def tls_config(p):
            """Build the stream-plane TLS config (or None when disabled);
            the single source both the server (DataStreamManagement) and
            the client (DataStreamOutput) construct from."""
            if p is None or not NettyConfigKeys.DataStreamTls.enabled(p):
                return None
            from ratis_tpu.transport.tcp import TcpTlsConfig
            K = NettyConfigKeys.DataStreamTls
            return TcpTlsConfig(cert_chain_path=K.cert_chain(p),
                                private_key_path=K.private_key(p),
                                trust_root_path=K.trust_root(p),
                                mutual_auth=K.mutual_auth(p))


class RaftClientConfigKeys:
    PREFIX = "raft.client"

    class Rpc:
        REQUEST_TIMEOUT_KEY = "raft.client.rpc.request.timeout"
        REQUEST_TIMEOUT_DEFAULT = TimeDuration.valueOf("3s")
        WATCH_REQUEST_TIMEOUT_KEY = "raft.client.rpc.watch.request.timeout"
        WATCH_REQUEST_TIMEOUT_DEFAULT = TimeDuration.valueOf("10s")

        @staticmethod
        def request_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(RaftClientConfigKeys.Rpc.REQUEST_TIMEOUT_KEY,
                                       RaftClientConfigKeys.Rpc.REQUEST_TIMEOUT_DEFAULT)

        @staticmethod
        def watch_request_timeout(p: RaftProperties) -> TimeDuration:
            return p.get_time_duration(
                RaftClientConfigKeys.Rpc.WATCH_REQUEST_TIMEOUT_KEY,
                RaftClientConfigKeys.Rpc.WATCH_REQUEST_TIMEOUT_DEFAULT)

    class Async:
        OUTSTANDING_REQUESTS_MAX_KEY = "raft.client.async.outstanding-requests.max"
        OUTSTANDING_REQUESTS_MAX_DEFAULT = 100

        @staticmethod
        def outstanding_requests_max(p: RaftProperties) -> int:
            return p.get_int(RaftClientConfigKeys.Async.OUTSTANDING_REQUESTS_MAX_KEY,
                             RaftClientConfigKeys.Async.OUTSTANDING_REQUESTS_MAX_DEFAULT)

    class MessageStream:
        SUBMESSAGE_SIZE_KEY = "raft.client.message-stream.submessage-size"
        SUBMESSAGE_SIZE_DEFAULT = "1MB"

        @staticmethod
        def submessage_size(p: RaftProperties) -> int:
            return p.get_size(RaftClientConfigKeys.MessageStream.SUBMESSAGE_SIZE_KEY,
                              RaftClientConfigKeys.MessageStream.SUBMESSAGE_SIZE_DEFAULT)
