"""String-keyed typed configuration with ${var} substitution.

Capability parity with the reference's RaftProperties
(ratis-common/src/main/java/org/apache/ratis/conf/RaftProperties.java:47):
a mutable map of dotted string keys to string values with typed getters,
`${other.key}` substitution (RaftProperties.java:149), plus a `Parameters`
side-channel for non-string objects (TLS configs etc.,
ratis-common/.../conf/Parameters.java).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, TypeVar

from ratis_tpu.util.timeduration import TimeDuration

_VAR = re.compile(r"\$\{([^}]+)\}")
_MAX_SUBST = 20

_SIZE_UNITS = {
    "b": 1, "k": 1 << 10, "kb": 1 << 10, "m": 1 << 20, "mb": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "t": 1 << 40, "tb": 1 << 40,
}


def parse_size(value: "str | int") -> int:
    """Parse '64KB', '8m', '1gb' -> bytes (cf. reference SizeInBytes.java)."""
    if isinstance(value, int):
        return value
    m = re.match(r"^\s*(-?\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*$", value)
    if not m:
        raise ValueError(f"cannot parse size {value!r}")
    num, unit = m.groups()
    if unit and unit.lower() not in _SIZE_UNITS:
        raise ValueError(f"unknown size unit {unit!r} in {value!r}")
    mult = _SIZE_UNITS[unit.lower()] if unit else 1
    return int(float(num) * mult)


class RaftProperties:
    def __init__(self, initial: Optional[dict[str, str]] = None):
        self._props: dict[str, str] = dict(initial or {})

    # -- raw ------------------------------------------------------------------

    def set(self, key: str, value: Any) -> None:
        self._props[key] = str(value)

    def unset(self, key: str) -> None:
        self._props.pop(key, None)

    def get_raw(self, key: str) -> Optional[str]:
        return self._props.get(key)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._props.get(key)
        if v is None:
            return default
        return self._substitute(v)

    def _substitute(self, value: str) -> str:
        for _ in range(_MAX_SUBST):
            m = _VAR.search(value)
            if not m:
                return value
            ref = self._props.get(m.group(1))
            if ref is None:
                return value
            value = value[:m.start()] + ref + value[m.end():]
        raise ValueError(f"too many substitutions resolving {value!r}")

    # -- typed getters/setters ----------------------------------------------

    def get_int(self, key: str, default: int) -> int:
        v = self.get(key)
        return default if v is None else int(v)

    def set_int(self, key: str, value: int) -> None:
        self.set(key, int(value))

    def get_float(self, key: str, default: float) -> float:
        v = self.get(key)
        return default if v is None else float(v)

    def get_boolean(self, key: str, default: bool) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("true", "1", "yes", "on")

    def set_boolean(self, key: str, value: bool) -> None:
        self.set(key, "true" if value else "false")

    def get_time_duration(self, key: str, default: "TimeDuration | str") -> TimeDuration:
        v = self.get(key)
        return TimeDuration.valueOf(default if v is None else v)

    def set_time_duration(self, key: str, value: "TimeDuration | str") -> None:
        self.set(key, str(TimeDuration.valueOf(value)))

    def get_size(self, key: str, default: "int | str") -> int:
        v = self.get(key)
        return parse_size(default if v is None else v)

    def get_enum(self, key: str, default):
        v = self.get(key)
        if v is None:
            return default
        return type(default)[v.strip().upper()]

    def items(self):
        return self._props.items()

    def clone(self) -> "RaftProperties":
        return RaftProperties(dict(self._props))

    def __len__(self) -> int:
        return len(self._props)

    def __contains__(self, key: str) -> bool:
        return key in self._props

    def __str__(self) -> str:
        return f"RaftProperties({len(self._props)} keys)"


class Parameters:
    """Typed non-string attachment map (reference Parameters.java)."""

    def __init__(self):
        self._map: dict[str, Any] = {}

    def put(self, key: str, value: Any) -> None:
        self._map[key] = value

    def get(self, key: str, expected_type: Optional[type] = None) -> Any:
        v = self._map.get(key)
        if v is not None and expected_type is not None and not isinstance(v, expected_type):
            raise TypeError(f"parameter {key}: expected {expected_type}, got {type(v)}")
        return v
