"""Dev tool: cProfile the bench load phase at N groups (not part of the
framework; run as
`python -m ratis_tpu.tools.profile_load [groups] [batched|scalar] [writes]
 [transport] [peers]`)."""
import asyncio
import cProfile
import io
import json
import pstats
import sys


def _force_cpu_platform():
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    _force_cpu_platform()
    groups = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    writes = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    batched = (sys.argv[2] != "scalar") if len(sys.argv) > 2 else True
    transport = sys.argv[4] if len(sys.argv) > 4 else "sim"
    peers = int(sys.argv[5]) if len(sys.argv) > 5 else 3
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def run():
        cluster = BenchCluster(groups, batched=batched, transport=transport,
                               num_servers=peers)
        try:
            await cluster.start()
            await cluster.run_load(1, 128)  # warmup
            prof = cProfile.Profile()
            prof.enable()
            result = await cluster.run_load(writes, 128)
            prof.disable()
            print("RESULT " + json.dumps(result))
            s = io.StringIO()
            ps = pstats.Stats(prof, stream=s).sort_stats("cumulative")
            ps.print_stats(45)
            print(s.getvalue())
            s = io.StringIO()
            ps = pstats.Stats(prof, stream=s).sort_stats("tottime")
            ps.print_stats(35)
            print(s.getvalue())
        finally:
            await cluster.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
