"""Conf-key / documentation drift check.

Every configuration key the code defines (``*_KEY = "raft..."`` constants
in ``ratis_tpu/conf/keys.py``) must appear in ``docs/configurations.md``,
and every key the doc names must exist in the code — PRs 2-3 each added
key families and the doc silently fell behind.  Run directly::

    python -m ratis_tpu.tools.check_conf_docs

or through the tier-1 test ``tests/test_conf_docs.py``.

Doc key grammar (inside backticks, in tables or prose):

- a full dotted key: ``raft.server.rpc.timeout.min``
- suffix alternation on ONE line: ``raft.x.y.min/.max`` or a later
  bare ``.suffix`` token — the suffix replaces the previous key's last
  segment (multi-segment suffixes replace one segment, so
  ``raft.a.b.enabled/.warn.threshold`` yields ``raft.a.b.warn.threshold``);
- a family wildcard: ``raft.grpc.tls.*`` — matches every code key under
  that prefix (and must match at least one, or the wildcard itself is
  drift).
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
KEYS_PY = os.path.join(_REPO, "ratis_tpu", "conf", "keys.py")
DOCS_MD = os.path.join(_REPO, "docs", "configurations.md")

_CODE_KEY_RE = re.compile(
    r'^\s*[A-Z0-9_]+_KEY\s*=\s*(?:\\\s*)?$|'
    r'_KEY\s*=\s*"(raft[a-z0-9_.\-]+)"')
# a _KEY assignment whose string literal wrapped to the next line
_CONT_STR_RE = re.compile(r'^\s*"(raft[a-z0-9_.\-]+)"')
_DOC_TOKEN_RE = re.compile(r"`([a-z0-9_.\-*/]+)`|"
                           r"(?<![`\w.])(raft\.[a-z0-9_.\-]+[a-z0-9_\-])")


def code_keys(path: str = KEYS_PY) -> set[str]:
    """Every dotted key string assigned to a ``*_KEY`` constant."""
    keys: set[str] = set()
    pending = False  # previous line was `X_KEY = \` (wrapped literal)
    for line in open(path):
        if pending:
            m = _CONT_STR_RE.match(line)
            if m:
                keys.add(m.group(1))
            pending = False
            continue
        m = re.search(r'_KEY\s*=\s*"(raft[a-z0-9_.\-]+)"', line)
        if m:
            keys.add(m.group(1))
        elif re.search(r'_KEY\s*=\s*\\\s*$', line):
            pending = True
    return keys


def doc_keys(path: str = DOCS_MD) -> tuple[set[str], set[str]]:
    """(exact keys, wildcard prefixes) named by the doc."""
    exact: set[str] = set()
    wildcards: set[str] = set()
    for line in open(path):
        if line.lstrip().startswith("#"):
            # section headings name namespaces (`raft.server.*`) for
            # orientation; only table/prose wildcards COVER keys
            continue
        last: str | None = None
        for m in _DOC_TOKEN_RE.finditer(line):
            token = m.group(1) or m.group(2)
            for part in token.split("/"):
                if not part:
                    continue
                if part.startswith("raft."):
                    if part.endswith(".*"):
                        wildcards.add(part[:-2])
                    else:
                        exact.add(part)
                        last = part
                elif part.startswith(".") and last is not None:
                    # suffix alternation: replace the previous key's last
                    # segment with this (possibly multi-segment) suffix
                    base = last.rsplit(".", 1)[0]
                    key = base + part
                    exact.add(key)
                    last = key
    return exact, wildcards


def doc_rows(path: str = DOCS_MD) -> dict[str, tuple[str, str]]:
    """key -> (default cell, meaning cell) for every key named in the
    first cell of a markdown table row.  Suffix alternation expands the
    same way as :func:`doc_keys`, and all expanded keys share the row's
    default/meaning cells (the doc writes them as ``a / b`` pairs)."""
    rows: dict[str, tuple[str, str]] = {}
    for line in open(path):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if len(cells) < 3 or set(cells[0]) <= {"-", " ", ":"}:
            continue  # not a row, or the |---|---| separator
        last: str | None = None
        for m in _DOC_TOKEN_RE.finditer(cells[0]):
            token = m.group(1) or m.group(2)
            for part in token.split("/"):
                if not part:
                    continue
                if part.startswith("raft."):
                    key = part[:-2] + ".*" if part.endswith(".*") else part
                    rows[key] = (cells[1], cells[2])
                    last = part if part.startswith("raft.") \
                        and not part.endswith(".*") else last
                elif part.startswith(".") and last is not None:
                    key = last.rsplit(".", 1)[0] + part
                    rows[key] = (cells[1], cells[2])
                    last = key
    return rows


def check() -> list[str]:
    """Drift findings; empty = code and docs agree."""
    code = code_keys()
    exact, wildcards = doc_keys()
    rows = doc_rows()
    problems: list[str] = []
    for key in sorted(code):
        if key in exact:
            # exact documentation must be a TABLE row carrying a default
            # and a meaning — a bare mention in prose reads as documented
            # while telling an operator nothing (the round-8 tightening:
            # every key gets a default-and-meaning row)
            row = rows.get(key)
            if row is None:
                covered = any(key.startswith(w + ".") for w in wildcards)
                if not covered:
                    problems.append(
                        f"key has no default-and-meaning table row in "
                        f"docs/configurations.md: {key}")
            elif not row[0] or not row[1]:
                problems.append(
                    f"table row for {key} is missing its "
                    f"{'default' if not row[0] else 'meaning'} cell")
            continue
        if any(key.startswith(w + ".") for w in wildcards):
            continue
        problems.append(f"key not documented in docs/configurations.md: "
                        f"{key}")
    for key in sorted(exact):
        if key not in code:
            problems.append(f"documented key missing from conf/keys.py: "
                            f"{key}")
    for w in sorted(wildcards):
        if not any(key.startswith(w + ".") for key in code):
            problems.append(f"documented wildcard matches no key: {w}.*")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} conf/doc drift problem(s)", file=sys.stderr)
        return 1
    print(f"ok: {len(code_keys())} keys in sync with docs/configurations.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
