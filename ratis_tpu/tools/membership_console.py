"""Membership console demo.

Capability parity with the reference membership example
(ratis-examples/src/main/java/org/apache/ratis/examples/membership/server/
Console.java:29, RaftCluster.java, CServer.java): an interactive console
hosting an in-process cluster of counter servers on real TCP ports, with
live membership changes driven through setConfiguration:

    update <p1,p2,...>  replace the membership with servers on these ports
    add <port>          add a peer
    remove <port>       remove a peer
    show                print current peers + roles
    incr / query        drive the counter state machine
    quit

Run: ``python -m ratis_tpu.tools.membership_console 5100,5101,5102``
Scriptable via :func:`run_script` (how the test drives it).
"""

from __future__ import annotations

import asyncio
import sys
from typing import Optional

from ratis_tpu.client import RaftClient
from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
from ratis_tpu.models.counter import CounterStateMachine
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.server.server import RaftServer


def _peer(port: int) -> RaftPeer:
    return RaftPeer(RaftPeerId.value_of(f"p{port}"),
                    address=f"127.0.0.1:{port}")


class MembershipCluster:
    """In-process counter cluster keyed by port (reference RaftCluster)."""

    def __init__(self):
        from ratis_tpu.transport import tcp  # registers the factory
        from ratis_tpu.transport.base import TransportFactory
        self.factory = TransportFactory.get("TCP")
        self.properties = RaftProperties()
        RaftServerConfigKeys.Rpc.set_timeout(self.properties, "300ms", "600ms")
        RaftServerConfigKeys.Log.set_use_memory(self.properties, True)
        self.group_id = RaftGroupId.random_id()
        self.servers: dict[int, RaftServer] = {}
        self._client: Optional[RaftClient] = None

    def group(self) -> RaftGroup:
        return RaftGroup.value_of(
            self.group_id, [_peer(p) for p in sorted(self.servers)])

    async def init(self, ports: list[int]) -> None:
        group = RaftGroup.value_of(self.group_id,
                                   [_peer(p) for p in sorted(ports)])
        for port in ports:
            await self._start_server(port, group)

    async def _start_server(self, port: int, group: Optional[RaftGroup]):
        peer = _peer(port)
        server = RaftServer(
            peer.id, peer.address,
            state_machine_registry=lambda gid: CounterStateMachine(),
            properties=self.properties, transport_factory=self.factory,
            group=group)
        await server.start()
        self.servers[port] = server
        return server

    async def client(self) -> RaftClient:
        if self._client is None:
            self._client = (RaftClient.builder()
                            .set_raft_group(self.group())
                            .set_transport(
                                self.factory.new_client_transport(
                                    self.properties))
                            .build())
        return self._client

    async def _reset_client(self) -> None:
        if self._client is not None:
            await self._client.close()
            self._client = None

    async def update(self, ports: list[int]) -> str:
        """Membership -> exactly ``ports`` (reference RaftCluster.update):
        start newcomers empty, setConfiguration, stop the removed."""
        current = set(self.servers)
        target = set(ports)
        # Newcomers start already hosting the group (reference CServer
        # constructs its RaftServer with the group): they come up as
        # followers and the leader's staging appenders catch them up.
        newcomer_group = RaftGroup.value_of(
            self.group_id, [_peer(p) for p in sorted(target)])
        for port in target - current:
            await self._start_server(port, group=newcomer_group)
        client = await self.client()
        reply = await client.admin().set_configuration(
            [_peer(p) for p in sorted(target)])
        if not reply.success:
            raise RuntimeError(f"setConfiguration failed: {reply.exception}")
        # wait until every member actually hosts the group — the conf commit
        # can land before a bootstrapped newcomer finishes creating its
        # division, and a client could otherwise pick it and get
        # GroupMismatch
        deadline = asyncio.get_running_loop().time() + 10.0
        while any(self.group_id not in self.servers[p].divisions
                  for p in target):
            if asyncio.get_running_loop().time() > deadline:
                raise RuntimeError("new members did not join in time")
            await asyncio.sleep(0.05)
        for port in current - target:
            server = self.servers.pop(port)
            await server.close()
        await self._reset_client()
        return f"membership is now {sorted(target)}"

    async def add(self, port: int) -> str:
        return await self.update(sorted(set(self.servers) | {port}))

    async def remove(self, port: int) -> str:
        return await self.update(sorted(set(self.servers) - {port}))

    async def show(self) -> str:
        lines = []
        for port, server in sorted(self.servers.items()):
            div = server.divisions.get(self.group_id)
            role = div.role.name if div is not None else "(no group)"
            lines.append(f"  {server.peer_id}@{server.address}: {role}")
        return "cluster peers:\n" + "\n".join(lines)

    async def incr(self) -> str:
        client = await self.client()
        reply = await client.io().send(b"INCREMENT")
        if not reply.success:
            raise RuntimeError(str(reply.exception))
        return f"counter = {reply.message.content.decode()}"

    async def query(self) -> str:
        client = await self.client()
        reply = await client.io().send_read_only(b"GET")
        if not reply.success:
            raise RuntimeError(str(reply.exception))
        return f"counter = {reply.message.content.decode()}"

    async def close(self) -> None:
        await self._reset_client()
        for server in self.servers.values():
            await server.close()
        self.servers.clear()


USAGE = """Commands:
  update <p1,p2,..>  replace membership
  add <port>         add a peer
  remove <port>      remove a peer
  show               list peers and roles
  incr               increment the counter
  query              read the counter
  quit               exit"""


async def execute(cluster: MembershipCluster, line: str) -> Optional[str]:
    parts = line.strip().split()
    if not parts:
        return ""
    cmd = parts[0].lower()
    if cmd == "show":
        return await cluster.show()
    if cmd == "add":
        return await cluster.add(int(parts[1]))
    if cmd == "remove":
        return await cluster.remove(int(parts[1]))
    if cmd == "update":
        return await cluster.update(
            [int(x) for x in parts[1].split(",") if x])
    if cmd == "incr":
        return await cluster.incr()
    if cmd == "query":
        return await cluster.query()
    if cmd == "quit":
        return None
    return USAGE


async def run_script(initial_ports: list[int], commands: list[str]
                     ) -> list[str]:
    """Drive the console non-interactively; returns one output per command."""
    cluster = MembershipCluster()
    await cluster.init(initial_ports)
    out = []
    try:
        for line in commands:
            result = await execute(cluster, line)
            if result is None:
                break
            out.append(result)
    finally:
        await cluster.close()
    return out


async def _interactive(ports: list[int]) -> None:
    cluster = MembershipCluster()
    await cluster.init(ports)
    print("Raft membership example.", USAGE, sep="\n")
    try:
        while True:
            line = await asyncio.to_thread(input, "> ")
            try:
                result = await execute(cluster, line)
            except Exception as e:  # keep the console alive on bad input
                print(f"error: {e}")
                continue
            if result is None:
                break
            print(result)
    finally:
        await cluster.close()


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: python -m ratis_tpu.tools.membership_console "
              "<port1,port2,...>")
        sys.exit(2)
    ports = [int(x) for x in sys.argv[1].split(",")]
    asyncio.run(_interactive(ports))


if __name__ == "__main__":
    main()
