"""Static gate against the O(G) Python tax creeping back into hot paths.

PR 15 moved the per-group host bookkeeping (heartbeat due-ness,
hibernation clocks, cache expiry, client-window GC, watch frontiers) into
the vectorized upkeep plane; the remaining ``for ... divisions`` walks in
the tick/sweep modules are a short, deliberate allowlist (the legacy-mode
sweep, the low-rate resync backstop, shutdown, introspection endpoints,
and the measured-baseline walk).  This gate AST-scans those modules for
any loop or comprehension whose iterable mentions ``divisions`` and fails
on a site that is not allowlisted — AND on an allowlist entry that no
longer matches anything, so the list can only shrink with the code.  Run
directly::

    python -m ratis_tpu.tools.check_hot_loops

or through the tier-1 test ``tests/test_hot_loops.py``.

Scope: only the modules on the tick/sweep call paths are scanned (chaos
harnesses, shell, and bench tooling legitimately walk the fleet).  A new
per-group walk belongs either behind a legacy-mode gate (and on the
allowlist, with a review) or — preferably — as a channel on the
UpkeepPlane.
"""

from __future__ import annotations

import ast
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Modules on the tick/sweep call paths (relative to the repo root).
SCANNED = (
    "ratis_tpu/server/server.py",
    "ratis_tpu/server/division.py",
    "ratis_tpu/server/leader.py",
    "ratis_tpu/server/upkeep.py",
    "ratis_tpu/server/watchdog.py",
    "ratis_tpu/server/pause_monitor.py",
    "ratis_tpu/metrics/timeseries.py",
    # the placement control loop must stay O(servers + k): it scores the
    # ledger/sketch rollups, never the division fleet
    "ratis_tpu/placement/policy.py",
    "ratis_tpu/placement/actuate.py",
    "ratis_tpu/placement/controller.py",
    # the mesh plane sits INSIDE the tick: sharding helpers must stay
    # pure jit-wrapper code — any divisions walk here would run per tick
    # on the fast path
    "ratis_tpu/parallel/__init__.py",
    "ratis_tpu/parallel/mesh.py",
)

# (file, qualified function) -> why this per-group walk is allowed to stay.
ALLOWLIST: dict[tuple[str, str], str] = {
    ("ratis_tpu/server/server.py", "HeartbeatScheduler._run"):
        "legacy-mode sweep (raft.tpu.upkeep.enabled unset)",
    ("ratis_tpu/server/server.py", "HeartbeatScheduler._plane_resync"):
        "low-rate O(G) re-arm backstop (raft.tpu.upkeep.resync-sweeps)",
    ("ratis_tpu/server/server.py", "RaftServer.close"):
        "shutdown, runs once",
    ("ratis_tpu/server/server.py", "RaftServer.get_division"):
        "error-path message formatting",
    ("ratis_tpu/server/server.py", "RaftServer.divisions_info"):
        "GET /divisions introspection endpoint",
    ("ratis_tpu/server/watchdog.py", "StallWatchdog.sample"):
        "watchdog cadence is seconds, not the sweep tick",
    ("ratis_tpu/server/pause_monitor.py",
     "PauseMonitor._step_down_leaders"):
        "pause recovery, runs only after a detected stall",
    ("ratis_tpu/metrics/timeseries.py", "legacy_division_walk"):
        "measured baseline the lag ledger replaced (bench/tests only)",
}


class _Finder(ast.NodeVisitor):
    """Collect (qualname, lineno) of every loop/comprehension whose
    iterable's source mentions ``divisions``."""

    def __init__(self) -> None:
        self.stack: list[str] = []
        self.sites: list[tuple[str, int]] = []

    def _qual(self) -> str:
        return ".".join(self.stack) or "<module>"

    def _check_iter(self, it: ast.AST, lineno: int) -> None:
        if "divisions" in ast.unparse(it):
            self.sites.append((self._qual(), lineno))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def visit_AsyncFor(self, node: ast.AsyncFor) -> None:
        self._check_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_iter(node.iter, getattr(node.iter, "lineno", 0))
        self.generic_visit(node)


def scan_source(rel: str, source: str) -> list[tuple[str, str, int]]:
    """(file, qualname, lineno) of every divisions-iteration in one file."""
    finder = _Finder()
    finder.visit(ast.parse(source))
    return [(rel, qual, lineno) for qual, lineno in finder.sites]


def check(repo: str = _REPO,
          scanned=SCANNED, allowlist=ALLOWLIST) -> list[str]:
    """Gate findings; empty = every per-group walk is accounted for."""
    sites: list[tuple[str, str, int]] = []
    for rel in scanned:
        path = os.path.join(repo, rel)
        sites.extend(scan_source(rel, open(path).read()))
    problems = []
    matched: set[tuple[str, str]] = set()
    for rel, qual, lineno in sites:
        key = (rel, qual)
        if key in allowlist:
            matched.add(key)
        else:
            problems.append(
                f"new per-group walk in a tick/sweep module: "
                f"{rel}:{lineno} ({qual}) — vectorize it through the "
                f"UpkeepPlane or gate it behind legacy mode + allowlist")
    for key in sorted(set(allowlist) - matched):
        problems.append(
            f"stale allowlist entry (no matching loop): {key[0]} "
            f"({key[1]}) — remove it from check_hot_loops.ALLOWLIST")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} hot-loop problem(s)", file=sys.stderr)
        return 1
    print(f"ok: {len(SCANNED)} tick/sweep modules scanned, "
          f"{len(ALLOWLIST)} allowlisted per-group walks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
