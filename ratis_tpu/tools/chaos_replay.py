"""Replay a recorded chaos artifact exactly.

A failing scenario writes a self-contained ``(seed, scenario, journal)``
artifact (``ratis_tpu.chaos.scenario.write_artifact``).  This tool

1. re-derives the scenario's step schedule from ``(name, seed, config)``
   and asserts it is BYTE-IDENTICAL to the recorded one (the
   determinism contract — if this fails, the artifact was produced by a
   different code version and the replay would be meaningless);
2. rebuilds the same cluster shape (servers, groups, transport, state
   machine, durability) and re-runs the scenario;
3. reports the fresh result next to the recorded one and exits 0 iff
   the replay PASSED (a fixed bug replays green; an unfixed one
   reproduces).

Usage::

    python -m ratis_tpu.tools.chaos_replay artifact.json
    python -m ratis_tpu.tools.chaos_replay artifact.json --show
    python -m ratis_tpu.tools.chaos_replay artifact.json --storage DIR
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
from typing import Optional

from ratis_tpu.chaos.faults import Step
from ratis_tpu.chaos.scenario import ARTIFACT_VERSION, Scenario
from ratis_tpu.chaos.scenarios import build_scenario


def load_artifact(path: str) -> dict:
    with open(path) as f:
        artifact = json.load(f)
    version = artifact.get("version")
    if version != ARTIFACT_VERSION:
        raise SystemExit(f"{path}: artifact version {version!r} != "
                         f"supported {ARTIFACT_VERSION}")
    return artifact


def rebuild_scenario(artifact: dict) -> Scenario:
    """Re-derive the schedule and assert bit-for-bit equality with the
    recorded one."""
    rec = artifact["scenario"]
    scenario = build_scenario(rec["name"], int(rec["seed"]),
                              rec.get("config"))
    recorded = tuple(Step.from_json(s) for s in rec.get("steps", []))
    if scenario.steps != recorded:
        lines = [f"  recorded: {s.to_json()}" for s in recorded]
        lines += [f"  derived:  {s.to_json()}" for s in scenario.steps]
        raise SystemExit(
            "schedule drift: the artifact's recorded steps do not match "
            "the schedule this code derives from (name, seed, config) — "
            "replay would not reproduce the recorded run\n"
            + "\n".join(lines))
    return scenario


async def replay(scenario: Scenario,
                 storage_root: Optional[str] = None) -> "ScenarioResult":
    from ratis_tpu.chaos.cluster import ChaosCluster
    from ratis_tpu.chaos.scenario import run_scenario
    cfg = scenario.config
    own_tmp = None
    if cfg.get("durable") and storage_root is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="ratis-chaos-replay-")
        storage_root = own_tmp.name
    cluster = ChaosCluster(
        int(cfg.get("servers", 3)), int(cfg.get("groups", 1)),
        transport=cfg.get("transport", "sim"),
        sm=cfg.get("sm", "recording"),
        storage_root=storage_root if cfg.get("durable") else None,
        seed=scenario.seed)
    try:
        await cluster.start()
        return await run_scenario(cluster, scenario)
    finally:
        await cluster.close()
        if own_tmp is not None:
            own_tmp.cleanup()


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="chaos_replay", description=__doc__.splitlines()[0])
    parser.add_argument("artifact", help="recorded chaos artifact JSON")
    parser.add_argument("--show", action="store_true",
                        help="print the schedule + recorded journal and "
                             "exit without running")
    parser.add_argument("--storage", default=None,
                        help="storage root for durable replays "
                             "(default: a fresh temp dir)")
    args = parser.parse_args(argv)

    artifact = load_artifact(args.artifact)
    scenario = rebuild_scenario(artifact)
    print(f"scenario {scenario.name} seed={scenario.seed} "
          f"({len(scenario.steps)} steps) — schedule matches artifact")
    if args.show:
        for s in scenario.steps:
            print(f"  t+{s.at_s:6.2f}s  {s.op:14s} {s.target} "
                  f"{dict(s.args) or ''}")
        print(f"recorded: passed={artifact['passed']} "
              f"error={artifact.get('error')}")
        for e in artifact.get("journal", []):
            print(f"  t+{e['t']:6.2f}s  {e['kind']}: {e['detail']}")
        return 0

    result = asyncio.run(replay(scenario, args.storage))
    print(f"recorded: passed={artifact['passed']} "
          f"error={artifact.get('error')}")
    print(f"replayed: passed={result.passed} error={result.error}")
    print(f"  slos={result.slos} checks={result.checks} "
          f"acked={result.acked} recovery_frac={result.recovery_frac}")
    for e in result.journal:
        print(f"  t+{e['t']:6.2f}s  {e['kind']}: {e['detail']}")
    return 0 if result.passed else 1


if __name__ == "__main__":
    sys.exit(main())
