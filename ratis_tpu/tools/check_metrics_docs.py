"""Metric-name / documentation drift check (mirrors ``check_conf_docs``).

Every metric name the code registers on a ``RatisMetricRegistry``
(``.counter("...")``, ``.timer("...")``, ``.histogram("...")``,
``.gauge("...", ...)``, and the ``labeled("name", ...)`` form inside any
of those) must be named in ``docs/metrics.md`` — PR 4 built the catalog
by hand and rounds 5-8 each added registry families the doc could
silently miss.  Run directly::

    python -m ratis_tpu.tools.check_metrics_docs

or through the tier-1 test ``tests/test_metrics_docs.py``.

Doc grammar: a metric is documented when its name appears in backticks
anywhere in docs/metrics.md; ``/``-separated alternatives inside one
backtick pair (``` `a`/`b` ``` or ``` `a/b` ```) each count, and a part
that starts lowercase with no capital boundary of its own is also tried
as a SUFFIX alternation on the previous part's trailing camel-case word
(``numRetryCacheHits/Misses`` names both counters).  Only string
literals register; dynamically composed names (f-strings, variables) are
the caller's responsibility and are skipped here.
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(_REPO, "ratis_tpu")
DOCS_MD = os.path.join(_REPO, "docs", "metrics.md")

# .counter("name"), .timer("name"), .histogram("name"),
# .gauge("name", ...), and labeled("name", ...) anywhere (labeled names
# always end up as registry names through one of the four).
_REG_RE = re.compile(
    r"\.(?:counter|timer|histogram|gauge)\(\s*\"([A-Za-z_][A-Za-z0-9_]*)\"")
_LABELED_RE = re.compile(r"\blabeled\(\s*\"([A-Za-z_][A-Za-z0-9_]*)\"")
_DOC_TOKEN_RE = re.compile(r"`([^`]+)`")
_WORD_SPLIT_RE = re.compile(r"[A-Z][a-z0-9]*$")


def code_metric_names(root: str = PKG) -> dict[str, list[str]]:
    """metric name -> files registering it (string-literal sites only)."""
    out: dict[str, list[str]] = {}
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py") or fn == "check_metrics_docs.py":
                continue  # this module's docstring names the grammar
            path = os.path.join(dirpath, fn)
            text = open(path).read()
            rel = os.path.relpath(path, _REPO)
            for m in (*_REG_RE.finditer(text), *_LABELED_RE.finditer(text)):
                out.setdefault(m.group(1), [])
                if rel not in out[m.group(1)]:
                    out[m.group(1)].append(rel)
    return out


def doc_metric_names(path: str = DOCS_MD) -> set[str]:
    """Every metric name the doc can be said to document."""
    names: set[str] = set()
    text = open(path).read()
    for m in _DOC_TOKEN_RE.finditer(text):
        token = m.group(1)
        # `dispatches{reason=...}` documents the labeled family name
        token = token.split("{", 1)[0]
        parts = [p for p in token.split("/") if p]
        prev = None
        for part in parts:
            part = part.strip().strip(".,;:()")
            if not part or " " in part:
                prev = None
                continue
            names.add(part)
            if prev is not None:
                # suffix alternation: `numRetryCacheHits/Misses` — replace
                # the previous name's trailing camel word with this part
                tail = _WORD_SPLIT_RE.search(prev)
                if tail is not None and part[0].isupper():
                    names.add(prev[:tail.start()] + part)
            prev = part
    return names


def check() -> list[str]:
    """Drift findings; empty = every registered metric is documented."""
    code = code_metric_names()
    doc = doc_metric_names()
    problems = []
    for name in sorted(code):
        if name not in doc:
            problems.append(
                f"metric not documented in docs/metrics.md: {name} "
                f"(registered in {', '.join(code[name])})")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} metric/doc drift problem(s)",
              file=sys.stderr)
        return 1
    print(f"ok: {len(code_metric_names())} registered metric names "
          f"covered by docs/metrics.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
