"""Segment-file dumper (reference ratis-tools ParseRatisLog.java:33):
decode a ``log_<s>-<e>`` / ``log_inprogress_<s>`` file and print each
entry's term/index/kind + a payload preview; also verifies record CRCs.

Usage: python -m ratis_tpu.tools.parse_log <segment-file> [...]
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

from ratis_tpu.protocol.logentry import LogEntry, LogEntryKind
from ratis_tpu.server.log.segmented import read_records


def dump_segment(path: str, out: Callable[[str], None] = print,
                 sm_format: Optional[Callable[[bytes], str]] = None) -> int:
    """Print every entry in one segment file; returns the entry count."""
    import os
    import pathlib
    payloads, good_len = read_records(pathlib.Path(path))
    file_size = os.path.getsize(path)
    out(f"# {path}: {len(payloads)} entries, {good_len}/{file_size} "
        f"valid bytes{' (TRUNCATED TAIL)' if good_len < file_size else ''}")
    count = 0
    for raw in payloads:
        entry = LogEntry.from_bytes(raw)
        if entry.kind == LogEntryKind.STATE_MACHINE and entry.smlog is not None:
            data = entry.smlog.log_data
            body = (sm_format(data) if sm_format is not None
                    else repr(data[:64]) + ("..." if len(data) > 64 else ""))
            detail = f"client={entry.smlog.client_id.hex()[:8]} " \
                     f"call={entry.smlog.call_id} data={body}"
        elif entry.kind == LogEntryKind.CONFIGURATION and entry.conf is not None:
            detail = "peers=[" + ", ".join(
                str(p.id) for p in entry.conf.peers) + "]"
            if entry.conf.old_peers:
                detail += " old=[" + ", ".join(
                    str(p.id) for p in entry.conf.old_peers) + "]"
        elif entry.kind == LogEntryKind.METADATA:
            detail = f"commitIndex={entry.commit_index}"
        else:
            detail = ""
        out(f"(t:{entry.term}, i:{entry.index}) {entry.kind.name} {detail}")
        count += 1
    return count


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    total = 0
    for path in argv:
        try:
            total += dump_segment(path)
        except Exception as e:
            print(f"error reading {path}: {e}", file=sys.stderr)
            return 1
    print(f"# total {total} entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
