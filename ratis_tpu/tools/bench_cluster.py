"""End-to-end multi-raft benchmark harness: the framework's own load
generator (reference analog: ratis-examples filestore LoadGen,
ratis-examples/src/main/java/org/apache/ratis/examples/filestore/cli/LoadGen.java,
driven against an in-process MiniRaftCluster-style trio).

Spins one in-process server trio over the simulated transport (direct
function-call RPC — measures the framework, not socket syscalls), hosts N
sibling RaftGroups on it (the multi-raft axis, RaftServerProxy.java:89-188),
elects all leaders, then drives concurrent counter writes through the full
client->leader->log->appender->quorum->apply->reply path, with the batched
quorum engine ticking every group on each server as ONE fused dispatch.

Reports aggregate commits/sec + p50/p99 commit latency — the north-star
metrics from BASELINE.md.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import sys
import time
from typing import Optional


def _ephemeral_port() -> int:
    """Ask the kernel for a currently-free localhost port."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
from ratis_tpu.models.counter import CounterStateMachine
from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                           NotLeaderException, RaftException)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.protocol.requests import RaftClientRequest, write_request_type
from ratis_tpu.server.server import RaftServer
from ratis_tpu.transport.simulated import (SimulatedNetwork,
                                           SimulatedTransportFactory)


def bench_properties(batched: bool, num_groups: int = 1,
                     hibernate: bool = False,
                     mesh_devices: int = 0,
                     num_servers: int = 3,
                     transport: str = "sim",
                     trace: bool = False,
                     trace_sample: int = 16) -> RaftProperties:
    from ratis_tpu.engine.engine import QuorumEngine
    p = RaftProperties()
    # Timeouts scale with CHANNEL density (groups x followers): background
    # heartbeat volume is O(channels / interval) — one appender item per
    # follower per group, like the reference — so a fixed 1s/2s that is
    # fine at 64 groups makes thousands of co-hosted channels spend the
    # whole host on idle upkeep (measured: 5-peer x 10240 = 40960 channels
    # at an 8s/16s-derived 4s sweep saturated the loop on heartbeat item
    # build+handle alone).  Multi-raft deployments tune exactly this knob
    # as density grows; both engine modes get the same setting, so the
    # batched/scalar comparison is unaffected.
    channels = num_groups * max(num_servers - 1, 1)
    if channels >= 2048:
        # the per-call rpc deadline scales with density too: at thousands
        # of channels a legitimately-busy handler on a loaded loop blows a
        # 3s deadline, and mass timeouts amplify into retry storms
        p.set(RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_KEY, "8s")
    if channels >= 32768:
        # margin over the sweep period matters as much as volume here: a
        # loaded sweep delivers late, and the election timeout must
        # tolerate a couple of late sweeps without deposing the leader
        RaftServerConfigKeys.Rpc.set_timeout(p, "24s", "48s")
    elif channels >= 16384:
        RaftServerConfigKeys.Rpc.set_timeout(p, "8s", "16s")
    elif channels >= (2048 if transport == "grpc" else 4096):
        # 2048 channels at 1s/2s was metastable through the costlier
        # grpc.aio transport: one hiccup tipped ~3000 divisions into
        # concurrent elections (measured: 3072 live candidacies, 4k
        # in-flight vote RPCs, multi-GB of pending call objects) and the
        # storm sustained itself.  One tier of margin removes the basin —
        # a deployment tunes this knob to its transport's per-op cost
        # (TCP's cheap framing holds 1s/2s at the same density).
        RaftServerConfigKeys.Rpc.set_timeout(p, "4s", "8s")
    else:
        # 1s/2s at <=1024 3-peer groups: already ~7x the reference's
        # default election timeouts (150-300ms, RaftServerConfigKeys.java)
        # — the baseline's per-(group,follower) heartbeat channels get a
        # generous but realistic idle cadence.
        RaftServerConfigKeys.Rpc.set_timeout(p, "1s", "2s")
    if batched:
        # Commits advance inline at ack intake (QuorumEngine.on_ack), so
        # the device tick only drives election timeouts (1-2s here) and
        # staleness sweeps: a 20ms cadence loses nothing while cutting the
        # per-dispatch overhead 10x — and each dispatch carries a 10x
        # larger packed event batch, which is exactly the shape the TPU
        # kernel wants.
        p.set("raft.tpu.engine.tick-interval", "20ms")
    else:
        p.set("raft.tpu.engine.tick-interval", "2ms")
    # Pre-size the engine so adding N groups never regrows the batch arrays
    # (each regrow is a new kernel shape -> a compile stall mid-run).
    p.set(RaftServerConfigKeys.Engine.MAX_GROUPS_KEY,
          str(max(QuorumEngine._bucket(num_groups), 64)))
    RaftServerConfigKeys.Log.set_use_memory(p, True)
    # server-level heap discipline (tuned thresholds + idle-janitor seal;
    # the harness calls seal_heap() right after bring-up instead of waiting
    # out the idle window)
    p.set(RaftServerConfigKeys.Gc.DISCIPLINE_KEY, "true")
    # steady-state re-freeze on every rung: the in-memory logs accrete
    # live entries under load and collector passes over them were
    # measured at 0.3-0.5s (gen1, 40k channels) up to 13.8s (gen2 over a
    # retry-storm-bloated young heap at 1024 gRPC groups) — collecting
    # ZERO every time.  The memory log never purges, so the refreeze
    # leak trade is moot here.
    p.set(RaftServerConfigKeys.Gc.REFREEZE_INTERVAL_KEY, "15s")
    if mesh_devices:
        # shard the resident engine state over the group axis of an
        # n-device mesh (parallel/mesh.py; the rung that gives sharding a
        # measured e2e number, not just dryrun bit-identity)
        p.set(RaftServerConfigKeys.Engine.MESH_DEVICES_KEY,
              str(mesh_devices))
    if trace:
        # host-path tracing (ratis_tpu.trace): every trace_sample-th write
        # records request->commit stage spans; exported by run_bench as the
        # host_path_decomposition block + Chrome trace-event JSON
        p.set(RaftServerConfigKeys.Trace.ENABLED_KEY, "true")
        p.set(RaftServerConfigKeys.Trace.SAMPLE_EVERY_KEY, str(trace_sample))
    if batched:
        # TPU-native execution mode: every tick runs the jitted kernel over
        # all groups, and append traffic toward each destination server is
        # folded into multi-group envelopes (data-path + heartbeat
        # coalescing — O(server pairs) RPCs instead of O(groups)).
        p.set("raft.tpu.engine.scalar-fallback-threshold", "0")
        p.set(RaftServerConfigKeys.Log.Appender.COALESCING_ENABLED_KEY, "true")
        p.set(RaftServerConfigKeys.Heartbeat.COALESCING_ENABLED_KEY, "true")
        # Wire write coalescing (raft.tpu.*, round 6): batch pending frames
        # into one buffered flush per connection — the per-frame
        # write()+drain() pair was the measured top host cost of the real
        # TCP path once consensus itself left the latency path.  100µs of
        # latency budget is noise against ~100ms commit p50; the byte
        # threshold flushes big batches early.  Scalar mode keeps the
        # reference's per-frame shape (these stay 0 there).
        from ratis_tpu.conf.keys import WireConfigKeys
        p.set(WireConfigKeys.Tcp.FLUSH_BYTES_KEY, "128KB")
        p.set(WireConfigKeys.Tcp.FLUSH_MICROS_KEY, "100")
        p.set(WireConfigKeys.Grpc.FLUSH_MICROS_KEY, "100")
        p.set(WireConfigKeys.Grpc.FLUSH_CHUNKS_KEY, "64")
        if hibernate:
            # idle-group quiescence (requires the coalesced heartbeat
            # channel): idle groups cost zero background traffic
            p.set(RaftServerConfigKeys.Hibernate.ENABLED_KEY, "true")
    else:
        # the reference's cost shape: one Python pass per group per event
        # (thread-per-division EventProcessor analog) and one RPC per
        # (group, follower) batch (GrpcLogAppender.java:356 stream-per-pair).
        p.set("raft.tpu.engine.scalar-fallback-threshold", "1000000000")
        p.set(RaftServerConfigKeys.Log.Appender.COALESCING_ENABLED_KEY, "false")
        p.set(RaftServerConfigKeys.Heartbeat.COALESCING_ENABLED_KEY, "false")
    return p


class BenchCluster:
    """An in-process ``num_servers``-server cluster (default 3) hosting
    ``num_groups`` sibling groups."""

    def __init__(self, num_groups: int, num_servers: int = 3,
                 batched: bool = True, transport: str = "sim",
                 sm: str = "counter", datastream: bool = False,
                 hibernate: bool = False, mesh_devices: int = 0,
                 trace: bool = False, trace_sample: int = 16):
        self.num_groups = num_groups
        self.batched = batched
        self.transport = transport
        self.sm = sm
        self.datastream = datastream
        self.hibernate = hibernate
        self.mesh_devices = mesh_devices
        self.trace = trace
        if transport in ("tcp", "grpc"):
            # Real localhost sockets: every RPC pays framing + syscalls, so
            # the per-(group,follower) stream shape costs what it costs the
            # reference — the rungs that prove the coalesced paths
            # (AppendEnvelope / BulkHeartbeat) survive a real transport.
            # "tcp" is the netty-analog framed transport; "grpc" is the
            # grpc.aio transport (reference's primary RPC stack analog).
            from ratis_tpu.transport.base import TransportFactory
            import ratis_tpu.transport.grpc  # noqa: F401  (registers GRPC)
            import ratis_tpu.transport.tcp  # noqa: F401  (registers TCP)
            self.network = None
            self.factory = TransportFactory.get(
                "GRPC" if transport == "grpc" else "TCP")
            peers = [RaftPeer(RaftPeerId.value_of(f"s{i}"),
                              address=f"127.0.0.1:{_ephemeral_port()}",
                              datastream_address=(
                                  f"127.0.0.1:{_ephemeral_port()}"
                                  if datastream else None))
                     for i in range(num_servers)]
        elif transport == "sim":
            self.network = SimulatedNetwork()
            self.factory = SimulatedTransportFactory(self.network)
            peers = [RaftPeer(RaftPeerId.value_of(f"s{i}"),
                              address=f"sim:s{i}",
                              datastream_address=(
                                  f"127.0.0.1:{_ephemeral_port()}"
                                  if datastream else None))
                     for i in range(num_servers)]
        else:
            raise ValueError(f"unknown bench transport {transport!r}")
        self.properties = bench_properties(batched, num_groups,
                                           hibernate=hibernate,
                                           mesh_devices=mesh_devices,
                                           num_servers=num_servers,
                                           transport=transport,
                                           trace=trace,
                                           trace_sample=trace_sample)
        if self.network is not None:
            # the sim's default 3s rpc deadline models a small cluster; a
            # legitimately-busy handler at thousands of co-hosted groups
            # (coalesced envelope / bulk chunk on a saturated loop) gets
            # the same density-scaled deadline the real transports get
            self.network.request_timeout_s = max(
                3.0, RaftServerConfigKeys.Rpc.timeout_min(
                    self.properties).seconds)
        self.groups = [RaftGroup.value_of(RaftGroupId.random_id(), peers)
                       for _ in range(num_groups)]
        if sm == "filestore":
            from ratis_tpu.models.filestore import FileStoreStateMachine

            def _sm_factory():
                return FileStoreStateMachine()
        elif sm == "arithmetic":
            from ratis_tpu.models.arithmetic import ArithmeticStateMachine

            def _sm_factory():
                return ArithmeticStateMachine()
        else:
            def _sm_factory():
                return CounterStateMachine()
        self.servers: list[RaftServer] = [
            RaftServer(p.id, p.address,
                       state_machine_registry=lambda gid: _sm_factory(),
                       properties=self.properties,
                       transport_factory=self.factory,
                       group=self.groups[0])
            for p in peers]
        self._call_ids = itertools.count(1)
        self.election_convergence_s: float = 0.0
        self.prewarm_s: float = 0.0
        self._leader_hint: dict[RaftGroupId, RaftServer] = {}

    async def start(self) -> None:
        if self.batched:
            # Compile every pad bucket before elections begin: a mid-run
            # compile stall is long enough to fire election timeouts.  The
            # jitted step is process-shared, so one engine warms all three.
            # Compilation is NOT part of election convergence (it is paid
            # once per process, not once per bring-up) — timed separately.
            tw = time.monotonic()
            buckets, b = [], 64
            from ratis_tpu.engine.engine import QuorumEngine
            top = max(QuorumEngine._bucket(self.num_groups), 64)
            while b <= max(top, 4096):
                buckets.append(b)
                b *= 4
            self.servers[0].engine.prewarm(
                group_counts=[x for x in buckets if x <= top],
                event_counts=buckets)
            self.prewarm_s = time.monotonic() - tw
        t0 = time.monotonic()
        await asyncio.gather(*(s.start() for s in self.servers))
        # Wave-wise group bring-up with APPOINTED-LEADER bootstrap: after
        # each wave's group-add, server 0's fresh divisions install
        # leadership directly (Division.bootstrap_as_leader — the
        # deployment mode where the operator chose the initial leader) —
        # no vote rounds at all.  At 10k 5-peer groups the per-group
        # election machinery (vote RPC fan-out + reply handling x 51200
        # divisions) was the dominant bring-up cost; randomized-timeout
        # elections remain as the fallback for any division the bootstrap
        # cannot claim (non-fresh state).
        import os
        trace = os.environ.get("RATIS_BENCH_TRACE")
        wave = 128
        await self._appoint_leaders([self.groups[0]])
        await self._wait_all_leaders([self.groups[0]])
        # Pipelined waves: wave k's leader-READY wait (startup entries
        # committing through real replication) overlaps wave k+1's
        # group-add + bootstrap — the two touch disjoint groups, and with
        # appointed leaders there are no elections to storm, so the old
        # add->elect->wait serialization was pure idle time.
        pending_wait: list[RaftGroup] = []
        for i in range(1, len(self.groups), wave):
            batch = self.groups[i:i + wave]
            tw = time.monotonic()
            await asyncio.gather(*(s.group_add(g) for g in batch
                                   for s in self.servers))
            t_add = time.monotonic() - tw
            await self._appoint_leaders(batch)
            if pending_wait:
                await self._wait_all_leaders(pending_wait)
            pending_wait = batch
            if trace:
                print(f"bench: wave@{i} add={t_add:.2f}s "
                      f"total={time.monotonic() - tw:.2f}s",
                      file=sys.stderr, flush=True)
        if pending_wait:
            await self._wait_all_leaders(pending_wait)
        self.election_convergence_s = time.monotonic() - t0

    async def _appoint_leaders(self, groups: list[RaftGroup]) -> None:
        boots = []
        for g in groups:
            d = self.servers[0].divisions.get(g.group_id)
            if d is not None and d.is_follower():
                boots.append(d.bootstrap_as_leader())
        if boots:
            results = await asyncio.gather(*boots, return_exceptions=True)
            for r in results:
                if isinstance(r, BaseException):
                    print(f"bench: bootstrap fell back to election: {r}",
                          file=sys.stderr, flush=True)

    async def _wait_all_leaders(self, groups: list[RaftGroup],
                                timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        pending = {g.group_id for g in groups}
        while pending and time.monotonic() < deadline:
            done = set()
            for gid in pending:
                for s in self.servers:
                    d = s.divisions.get(gid)
                    if d is not None and d.is_leader() \
                            and d.leader_ctx is not None \
                            and d.leader_ctx.leader_ready.done():
                        self._leader_hint[gid] = s
                        done.add(gid)
                        break
            pending -= done
            if pending:
                await asyncio.sleep(0.05)
        if pending:
            raise TimeoutError(
                f"{len(pending)}/{len(groups)} groups in this wave have no "
                f"ready leader after {timeout}s")

    async def close(self) -> None:
        await asyncio.gather(*(s.close() for s in self.servers),
                             return_exceptions=True)

    # ------------------------------------------------------------- workload

    async def _write(self, client, client_id: ClientId, gid: RaftGroupId,
                     timeout: float = 0.0, message: bytes = b"INCREMENT"):
        """One write with leader-hint failover."""
        if not timeout:
            # a saturated 10k-group loop can starve one write past a fixed
            # 60s while the aggregate is perfectly healthy
            timeout = 60.0 if self.num_groups < 8192 else 240.0
        server = self._leader_hint.get(gid, self.servers[0])
        deadline = time.monotonic() + timeout
        from ratis_tpu.trace.tracer import STAGE_CLIENT, TRACER
        while True:
            # bounded per-attempt deadline: one stuck call must cost one
            # attempt, not the write's whole retry budget (the client
            # transport's 30s default ate 2 of the 60s budget per hang)
            trace_id = TRACER.begin_trace()
            req = RaftClientRequest(client_id, server.peer_id, gid,
                                    next(self._call_ids),
                                    Message.value_of(message),
                                    type=write_request_type(),
                                    timeout_ms=10_000.0,
                                    trace_id=trace_id)
            t0 = TRACER.now() if trace_id else 0
            try:
                reply = await client.send_request(server.address, req)
            except (RaftException, asyncio.TimeoutError):
                reply = None
            finally:
                if trace_id:
                    TRACER.record(trace_id, STAGE_CLIENT, t0, TRACER.now())
            if reply is not None and reply.success:
                self._leader_hint[gid] = server
                return reply
            if time.monotonic() > deadline:
                raise TimeoutError(f"write to {gid} kept failing")
            exc = reply.exception if reply is not None else None
            if isinstance(exc, NotLeaderException) \
                    and exc.suggested_leader is not None:
                by_id = {s.peer_id: s for s in self.servers}
                server = by_id.get(exc.suggested_leader.id, server)
            elif isinstance(exc, LeaderNotReadyException):
                await asyncio.sleep(0.01)
            else:
                idx = self.servers.index(server)
                server = self.servers[(idx + 1) % len(self.servers)]
                await asyncio.sleep(0.01)

    async def run_load(self, writes_per_group: int,
                       concurrency: int = 256,
                       message_factory=None,
                       active_groups: Optional[int] = None) -> dict:
        """Drive writes_per_group sequential writes per group, groups
        concurrent under a global in-flight bound; returns throughput and
        latency percentiles.  ``message_factory`` builds per-write payloads
        (default: the counter INCREMENT).  ``active_groups`` restricts the
        load to the first N groups — the sparse multi-tenant shape where
        most hosted groups are cold."""
        # properties matter here: the client plane gets the same wire
        # coalescing conf as the servers (raft.tpu.tcp/grpc flush keys)
        client = self.factory.new_client_transport(self.properties)
        sem = asyncio.Semaphore(concurrency)
        latencies: list[float] = []
        target_groups = (self.groups if active_groups is None
                         else self.groups[:active_groups])

        import os
        trace = os.environ.get("RATIS_BENCH_TRACE")
        failures: list[str] = []

        async def group_load(g: RaftGroup):
            client_id = ClientId.random_id()
            for _ in range(writes_per_group):
                async with sem:
                    msg = (message_factory() if message_factory is not None
                           else b"INCREMENT")
                    t0 = time.monotonic()
                    try:
                        await self._write(client, client_id, g.group_id,
                                          message=msg)
                    except TimeoutError as e:
                        # ONE write exhausting its retry budget must be
                        # REPORTED, not abort a multi-thousand-write rung
                        # (observed ~1/20k over grpc under load); the rung
                        # still fails loudly past a 1% fraction below
                        failures.append(str(g.group_id))
                        print(f"bench: WRITE FAILED {g.group_id}: {e}",
                              file=sys.stderr, flush=True)
                        continue
                    latencies.append(time.monotonic() - t0)
                    if trace and len(latencies) % 4096 == 0:
                        print(f"bench: {len(latencies)} writes done "
                              f"({len(latencies) / (time.monotonic() - t_start):.0f}/s)",
                              file=sys.stderr, flush=True)

        t_start = time.monotonic()
        await asyncio.gather(*(group_load(g) for g in target_groups))
        elapsed = time.monotonic() - t_start

        total = len(target_groups) * writes_per_group
        if not latencies or len(failures) > max(8, total // 100):
            raise TimeoutError(
                f"{len(failures)}/{total} writes failed — not a tail "
                f"event, the rung is broken: {failures[:5]}")
        latencies.sort()
        n = len(latencies)
        return {
            "commits": total - len(failures),
            "write_failures": len(failures),
            "elapsed_s": round(elapsed, 3),
            "commits_per_sec": round((total - len(failures)) / elapsed, 1),
            "p50_ms": round(latencies[n // 2] * 1e3, 2),
            "p99_ms": round(latencies[min(n - 1, (n * 99) // 100)] * 1e3, 2),
            "election_convergence_s": round(self.election_convergence_s, 2),
            "prewarm_s": round(self.prewarm_s, 2),
        }




@contextlib.asynccontextmanager
async def _started_cluster(num_groups: int, batched: bool,
                           transport: str = "sim", sm: str = "counter",
                           datastream: bool = False, num_servers: int = 3,
                           hibernate: bool = False, mesh_devices: int = 0,
                           trace: bool = False, trace_sample: int = 16):
    """Shared rung scaffold: build + start the cluster with the GC tuning
    every rung needs (defer gen-2 cascades during bring-up, then freeze the
    post-bring-up heap out of the collector — a single gen-2 pass over the
    10k-group live heap measured 52s; the pause monitor caught it)."""
    import gc
    # Bring-up allocates a few million long-lived objects; automatic gen-2
    # passes over that growing heap measured 0.5-1.25s pauses at 4096
    # 5-peer groups (they fire election timeouts -> storms) and tens of
    # seconds at 10k+.  Nothing allocated during bring-up is garbage, so
    # the harness runs with GC OFF while building, then takes the server
    # runtime's one deliberate seal (raft.tpu.gc.discipline supplies the
    # thresholds; RaftServer.seal_heap is the production knob — a server
    # without this harness gets the same seal from its idle janitor).
    gc.disable()
    cluster = None
    try:
        cluster = BenchCluster(num_groups, num_servers=num_servers,
                               batched=batched, transport=transport,
                               sm=sm, datastream=datastream,
                               hibernate=hibernate,
                               mesh_devices=mesh_devices,
                               trace=trace, trace_sample=trace_sample)
        await cluster.start()
        cluster.servers[0].seal_heap()
        gc.enable()
        yield cluster
    finally:
        gc.enable()
        if cluster is not None:
            await cluster.close()


async def run_bench(num_groups: int, writes_per_group: int,
                    batched: bool = True, concurrency: int = 256,
                    warmup_writes: int = 1, transport: str = "sim",
                    sm: str = "counter", num_servers: int = 3,
                    hibernate: bool = False, active_groups=None,
                    settle_s: float = 0.0, mesh_devices: int = 0,
                    teardown: bool = True, trace: bool = False,
                    trace_sample: int = 16,
                    trace_out: "str | None" = None) -> dict:
    """One ladder rung: build the ``num_servers``-server cluster, elect,
    warm up, measure, tear down.  ``teardown=False`` skips the graceful
    close: a measurement child that exits right after reporting has no
    business spending minutes unwinding 50k divisions (measured: the
    5-peer 10240 rung's close ran LONGER than its measurement; the OS
    reclaims an exiting process instantly).  ``trace`` enables host-path
    tracing (ratis_tpu.trace) over the measured window and attaches the
    ``host_path_decomposition`` block; ``trace_out`` additionally writes
    the Chrome trace-event JSON (Perfetto-loadable) to that path."""
    cm = _started_cluster(num_groups, batched, transport=transport,
                          sm=sm, num_servers=num_servers,
                          hibernate=hibernate, mesh_devices=mesh_devices,
                          trace=trace, trace_sample=trace_sample)
    cluster = await cm.__aenter__()
    try:
        if hibernate and settle_s:
            # let idle groups actually fall asleep before measuring
            await asyncio.sleep(settle_s)
        mf = None
        if sm == "arithmetic":
            # BASELINE config 2's workload shape: var = expression writes
            import itertools as _it
            seq = _it.count()
            mf = lambda: f"v{next(seq) % 7}={next(seq) % 97}+1".encode()
        if warmup_writes:
            await cluster.run_load(warmup_writes, concurrency,
                                   message_factory=mf,
                                   active_groups=active_groups)
        if trace:
            # decompose the MEASURED window only, not warmup/bring-up
            from ratis_tpu.trace import get_tracer
            get_tracer().reset()
        result = await cluster.run_load(writes_per_group, concurrency,
                                        message_factory=mf,
                                        active_groups=active_groups)
        if trace:
            from ratis_tpu.trace import get_tracer
            from ratis_tpu.trace.export import (host_path_decomposition,
                                                write_chrome_trace)
            records = get_tracer().snapshot()
            result["host_path_decomposition"] = \
                host_path_decomposition(records)
            dropped = get_tracer().stage_dropped()
            if dropped:
                # never a silent cap: wraparound means the table covers the
                # tail of the window, not all of it
                result["host_path_decomposition"]["rings_dropped"] = dropped
            if trace_out:
                import os
                write_chrome_trace(trace_out, records)
                result["trace_out"] = os.path.abspath(trace_out)
        engines = [s.engine for s in cluster.servers]
        result["batched_dispatches"] = sum(
            e.metrics["batched_dispatches"] for e in engines)
        result["engine_ticks"] = sum(e.metrics["ticks"] for e in engines)
        # wire fast-path observability: INCONSISTENCY rewinds (should be ~0
        # with the keyed stream dispatch), encode-once reuse, gRPC framing
        # batches — the evidence the round-6 hot-path work actually engaged
        result["append_rewinds"] = sum(
            s2.replication.metrics.get("rewinds", 0)
            for s2 in cluster.servers)
        from ratis_tpu.server.replication import ReplicationScheduler
        result["codec"] = ReplicationScheduler.codec_stats()
        if transport == "grpc":
            result["grpc_dispatch"] = {
                k: sum(s2.transport.dispatch_metrics.get(k, 0)
                       for s2 in cluster.servers)
                for k in ("stream_chunks", "keyed_chunks", "ordered_waits",
                          "batched_messages", "reply_batches")}
        for reason in ("dispatch_upload", "dispatch_commit",
                       "dispatch_dirty", "dispatch_votes",
                       "dispatch_sweep", "dispatch_backlog"):
            v = sum(e.metrics.get(reason, 0) for e in engines)
            if v:
                result[reason] = v
        result["groups"] = num_groups
        result["mode"] = "batched" if batched else "scalar"
        result["transport"] = transport
        result["peers"] = num_servers
        if active_groups is not None:
            result["active_groups"] = active_groups
        if hibernate:
            result["hibernate"] = True
            result["hibernated_groups"] = sum(
                1 for s2 in cluster.servers
                for d in s2.divisions.values() if d._hibernating)
        return result
    finally:
        if teardown:
            await cm.__aexit__(None, None, None)


async def run_churn_bench(num_groups: int, writes_per_group: int,
                          transfers: int, batched: bool = True,
                          concurrency: int = 128) -> dict:
    """BASELINE config 4 analog: reconfig/leadership churn under load.

    Drives the normal write load while a churn task performs ``transfers``
    leadership transfers (the reference's TransferLeadership admin path)
    on randomly chosen groups; measures how throughput and tail latency
    hold up while leaderships move underneath the clients."""
    import random

    from ratis_tpu.protocol.admin import TransferLeadershipArguments
    from ratis_tpu.protocol.requests import RequestType, admin_request_type

    async with _started_cluster(num_groups, batched) as cluster:
        client = cluster.factory.new_client_transport()
        rng = random.Random(17)
        churn_stats = {"ok": 0, "failed": 0}

        async def churn():
            client_id = ClientId.random_id()
            by_id = {s.peer_id: s for s in cluster.servers}
            for _ in range(transfers):
                g = rng.choice(cluster.groups)
                leader_srv = cluster._leader_hint.get(g.group_id,
                                                      cluster.servers[0])
                target = rng.choice(
                    [p.id for p in g.peers if p.id != leader_srv.peer_id])
                args = TransferLeadershipArguments(str(target), 3000.0)
                try:
                    # an earlier transfer may have moved this group's
                    # leadership: follow the NotLeader suggestion like any
                    # real admin client (the reference's client retry
                    # policy does exactly this) — bounded to the peer count
                    reply = None
                    for _attempt in range(2 * len(g.peers)):
                        req = RaftClientRequest(
                            client_id, leader_srv.peer_id, g.group_id,
                            next(cluster._call_ids),
                            Message(args.to_payload()),
                            type=admin_request_type(
                                RequestType.TRANSFER_LEADERSHIP),
                            timeout_ms=5000.0)
                        reply = await client.send_request(
                            leader_srv.address, req)
                        exc = reply.exception
                        if reply.success:
                            break
                        if isinstance(exc, LeaderNotReadyException):
                            # transfer raced a just-won election: the new
                            # leader serves admin ops once its startup
                            # entry commits — moments away
                            await asyncio.sleep(0.1)
                            continue
                        if not isinstance(exc, NotLeaderException) \
                                or exc.suggested_leader is None:
                            break
                        leader_srv = by_id.get(exc.suggested_leader.id,
                                               leader_srv)
                        # transferring "away from the leader" must track
                        # the real leader, or we'd ask it to transfer to
                        # itself
                        if target == leader_srv.peer_id:
                            target = rng.choice(
                                [p.id for p in g.peers
                                 if p.id != leader_srv.peer_id])
                            args = TransferLeadershipArguments(
                                str(target), 3000.0)
                    if reply is not None and reply.success:
                        churn_stats["ok"] += 1
                        cluster._leader_hint[g.group_id] = by_id.get(
                            target, cluster.servers[0])
                    else:
                        churn_stats["failed"] += 1
                        exc = reply.exception if reply is not None else None
                        churn_stats.setdefault("failures", []).append(
                            type(exc).__name__ if exc else "no-exception")
                        print(f"bench: transfer {g.group_id} -> {target} "
                              f"REJECTED: {exc}", file=sys.stderr, flush=True)
                except Exception as e:
                    churn_stats["failed"] += 1
                    churn_stats.setdefault("failures", []).append(
                        type(e).__name__)
                    print(f"bench: transfer {g.group_id} -> {target} "
                          f"FAILED: {type(e).__name__}: {e}",
                          file=sys.stderr, flush=True)
                await asyncio.sleep(0.02)

        churn_task = asyncio.create_task(churn())
        result = await cluster.run_load(writes_per_group, concurrency)
        await churn_task
        result["groups"] = num_groups
        result["mode"] = "batched" if batched else "scalar"
        result["transfers_ok"] = churn_stats["ok"]
        result["transfers_failed"] = churn_stats["failed"]
        result["transfer_failures"] = churn_stats.get("failures", [])
        return result


async def run_mixed_bench(num_groups: int, writes_per_group: int,
                          streams: int, stream_bytes: int,
                          batched: bool = True,
                          concurrency: int = 128) -> dict:
    """BASELINE config 5 analog: filestore + DataStream mixed load.

    Every group runs a FileStore state machine; the bulk load is ordinary
    log-path file writes, while ``streams`` concurrent DataStream file
    streams (stream_bytes each) ride the out-of-band stream plane into a
    subset of groups (ratis-examples filestore LoadGen's mixed mode)."""
    import msgpack

    from ratis_tpu.client import RaftClient

    async with _started_cluster(num_groups, batched, sm="filestore",
                                datastream=True) as cluster:
        stream_stats = {"ok": 0, "failed": 0, "bytes": 0, "elapsed_s": 0.0}
        payload = b"\x5a" * stream_bytes

        async def one_stream(i: int):
            g = cluster.groups[i % len(cluster.groups)]
            client = (RaftClient.builder()
                      .set_raft_group(g)
                      .set_transport(cluster.factory.new_client_transport(
                          cluster.properties))
                      .set_properties(cluster.properties)
                      .build())
            try:
                cmd = msgpack.packb({"op": "stream",
                                     "path": f"stream-{i}.bin"},
                                    use_bin_type=True)
                out = await client.data_stream().stream(cmd)
                for off in range(0, stream_bytes, 64 << 10):
                    await out.write_async(payload[off:off + (64 << 10)])
                reply = await out.close_async()
                if reply.success:
                    stream_stats["ok"] += 1
                    stream_stats["bytes"] += stream_bytes
                else:
                    # CLASSIFIED, never silent: a failing stream under load
                    # is a correctness signal, not a throughput footnote
                    stream_stats["failed"] += 1
                    exc = type(reply.exception).__name__ \
                        if reply.exception else "no-exception"
                    stream_stats.setdefault("failures", []).append(exc)
                    print(f"bench: stream {i} REJECTED: {exc}: "
                          f"{reply.exception}", file=sys.stderr, flush=True)
            except Exception as e:
                stream_stats["failed"] += 1
                stream_stats.setdefault("failures", []).append(
                    type(e).__name__)
                print(f"bench: stream {i} FAILED: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
            finally:
                await client.close()

        async def stream_load():
            # stream bandwidth is timed over the STREAM work only, not the
            # (longer) concurrent write load
            t0 = time.monotonic()
            sem = asyncio.Semaphore(8)

            async def bounded(i):
                async with sem:
                    await one_stream(i)

            await asyncio.gather(*(bounded(i) for i in range(streams)))
            stream_stats["elapsed_s"] = time.monotonic() - t0

        seq = itertools.count()
        msg_factory = lambda: msgpack.packb(
            {"op": "write", "path": f"w{next(seq)}", "data": b"x" * 128},
            use_bin_type=True)
        stream_task = asyncio.create_task(stream_load())
        result = await cluster.run_load(writes_per_group, concurrency,
                                        message_factory=msg_factory)
        await stream_task
        result["groups"] = num_groups
        result["mode"] = "batched" if batched else "scalar"
        result["streams_ok"] = stream_stats["ok"]
        result["streams_failed"] = stream_stats["failed"]
        result["stream_failures"] = stream_stats.get("failures", [])
        result["stream_mb_per_s"] = round(
            stream_stats["bytes"]
            / max(stream_stats["elapsed_s"], 1e-9) / (1 << 20), 2)
        return result


async def run_stream_throughput_bench(streams: int, stream_mb: int,
                                      packet_kb: int = 1024,
                                      window: int = 32) -> dict:
    """Dedicated DataStream THROUGHPUT rung: few concurrent streams moving
    tens of MB each over real TCP with big packets — the bulk-bytes job the
    out-of-band plane exists for (reference NettyClientStreamRpc /
    DataStreamManagement; the mixed rung measures coexistence with raft
    load, this one measures the pipe)."""
    import msgpack

    from ratis_tpu.client import RaftClient

    async with _started_cluster(max(streams, 4), True, sm="filestore",
                                datastream=True) as cluster:
        stream_bytes = stream_mb << 20
        packet = packet_kb << 10
        payload = b"\x5a" * packet
        stats = {"ok": 0, "failed": 0, "bytes": 0, "failures": []}

        async def one(i: int):
            g = cluster.groups[i % len(cluster.groups)]
            client = (RaftClient.builder()
                      .set_raft_group(g)
                      .set_transport(cluster.factory.new_client_transport(
                          cluster.properties))
                      .set_properties(cluster.properties)
                      .build())
            try:
                cmd = msgpack.packb({"op": "stream", "path": f"bulk-{i}.bin"},
                                    use_bin_type=True)
                out = await client.data_stream().stream(cmd, window=window)
                for _ in range(stream_bytes // packet):
                    await out.write_async(payload)
                reply = await out.close_async()
                if reply.success:
                    stats["ok"] += 1
                    stats["bytes"] += stream_bytes
                else:
                    stats["failed"] += 1
                    stats["failures"].append(
                        type(reply.exception).__name__
                        if reply.exception else "no-exception")
            except Exception as e:
                stats["failed"] += 1
                stats["failures"].append(type(e).__name__)
                print(f"bench: bulk stream {i} FAILED: "
                      f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
            finally:
                await client.close()

        t0 = time.monotonic()
        await asyncio.gather(*(one(i) for i in range(streams)))
        elapsed = time.monotonic() - t0
        return {
            "streams": streams,
            "stream_mb": stream_mb,
            "packet_kb": packet_kb,
            "streams_ok": stats["ok"],
            "streams_failed": stats["failed"],
            "stream_failures": stats["failures"],
            "stream_mb_per_s": round(
                stats["bytes"] / max(elapsed, 1e-9) / (1 << 20), 2),
            "elapsed_s": round(elapsed, 2),
        }
